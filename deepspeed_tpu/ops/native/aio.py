"""ctypes binding for the async-IO library (csrc/aio.cpp) — the reference's
AsyncIOBuilder/aio_handle surface (ops/aio, csrc/aio/py_lib)."""

import ctypes

import numpy as np

from deepspeed_tpu.ops.native.builder import AsyncIOBuilder

_lib = None


def load():
    global _lib
    if _lib is None:
        lib = AsyncIOBuilder().load()
        lib.aio_handle_create.restype = ctypes.c_void_p
        lib.aio_handle_create.argtypes = [ctypes.c_int64, ctypes.c_int,
                                          ctypes.c_int, ctypes.c_int,
                                          ctypes.c_int]
        lib.aio_handle_create2.restype = ctypes.c_void_p
        lib.aio_handle_create2.argtypes = [ctypes.c_int64, ctypes.c_int,
                                           ctypes.c_int, ctypes.c_int,
                                           ctypes.c_int, ctypes.c_int]
        lib.aio_handle_backend.argtypes = [ctypes.c_void_p]
        lib.aio_handle_backend.restype = ctypes.c_int
        lib.aio_handle_destroy.argtypes = [ctypes.c_void_p]
        lib.aio_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.aio_open.restype = ctypes.c_int
        lib.aio_close.argtypes = [ctypes.c_int]
        for fn in (lib.aio_pread, lib.aio_pwrite):
            fn.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
                           ctypes.c_int64, ctypes.c_int64]
            fn.restype = None
        for fn in (lib.aio_sync_pread, lib.aio_sync_pwrite):
            fn.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
                           ctypes.c_int64, ctypes.c_int64]
            fn.restype = ctypes.c_int64
        lib.aio_handle_wait.argtypes = [ctypes.c_void_p]
        lib.aio_handle_wait.restype = ctypes.c_int64
        lib.aio_handle_errors.argtypes = [ctypes.c_void_p]
        lib.aio_handle_errors.restype = ctypes.c_int64
        _lib = lib
    return _lib


class AsyncIOHandle:
    """Python face of aio_handle_t (reference
    deepspeed_py_aio_handle.cpp:14-33): block_size/queue_depth/
    single_submit/overlap_events/thread_count knobs, async_pread/pwrite +
    wait."""

    def __init__(self, block_size=1048576, queue_depth=8, single_submit=False,
                 overlap_events=True, thread_count=1, backend="auto"):
        """``backend``: "auto" (io_uring when the kernel allows, else the
        thread pool), "threads", or "io_uring" (raises if unsupported)."""
        self.lib = load()
        self.block_size = block_size
        self.queue_depth = queue_depth
        self.single_submit = single_submit
        self.overlap_events = overlap_events
        self.thread_count = thread_count
        codes = {"auto": 0, "threads": 1, "io_uring": 2}
        if backend not in codes:
            raise ValueError(f"backend must be one of {sorted(codes)}, "
                             f"got {backend!r}")
        self._h = self.lib.aio_handle_create2(
            block_size, queue_depth, thread_count,
            int(single_submit), int(overlap_events), codes[backend])
        if not self._h:
            raise OSError("io_uring backend requested but unsupported by "
                          "this kernel/seccomp profile")

    @property
    def backend(self):
        return "io_uring" if self.lib.aio_handle_backend(self._h) else "threads"

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self.lib.aio_handle_destroy(self._h)
                self._h = None
        except Exception:
            pass

    # -- file helpers ------------------------------------------------------
    def open(self, path, for_write):
        fd = self.lib.aio_open(str(path).encode(), int(for_write))
        if fd < 0:
            raise OSError(f"aio_open failed for {path}")
        return fd

    def close(self, fd):
        self.lib.aio_close(fd)

    @staticmethod
    def _buf(arr):
        assert isinstance(arr, np.ndarray) and arr.flags["C_CONTIGUOUS"]
        return arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes

    # -- async API (reference async_pread/async_pwrite + wait) -------------
    def async_pread(self, arr, fd, offset=0):
        ptr, nbytes = self._buf(arr)
        self.lib.aio_pread(self._h, fd, ptr, nbytes, offset)

    def async_pwrite(self, arr, fd, offset=0):
        ptr, nbytes = self._buf(arr)
        self.lib.aio_pwrite(self._h, fd, ptr, nbytes, offset)

    def wait(self):
        done = self.lib.aio_handle_wait(self._h)
        self._raise_errors()
        return done

    def _raise_errors(self):
        # aio_handle_errors returns-and-clears, so a failure is reported once
        # (to the wait that observed it) and does not poison later batches
        n = self.lib.aio_handle_errors(self._h)
        if n:
            raise IOError(f"{n} async IO request(s) failed")

    # -- sync API (reference sync_pread/sync_pwrite) ------------------------
    def sync_pread(self, arr, path_or_fd, offset=0):
        fd, opened = self._fd(path_or_fd, False)
        try:
            ptr, nbytes = self._buf(arr)
            done = self.lib.aio_sync_pread(self._h, fd, ptr, nbytes, offset)
            self._raise_errors()
            return done
        finally:
            if opened:
                self.close(fd)

    def sync_pwrite(self, arr, path_or_fd, offset=0):
        fd, opened = self._fd(path_or_fd, True)
        try:
            ptr, nbytes = self._buf(arr)
            done = self.lib.aio_sync_pwrite(self._h, fd, ptr, nbytes, offset)
            self._raise_errors()
            return done
        finally:
            if opened:
                self.close(fd)

    def _fd(self, path_or_fd, for_write):
        if isinstance(path_or_fd, int):
            return path_or_fd, False
        return self.open(path_or_fd, for_write), True
