"""ctypes binding for the async-IO library (csrc/aio.cpp) — the reference's
AsyncIOBuilder/aio_handle surface (ops/aio, csrc/aio/py_lib).

O_DIRECT mode (ISSUE 20): ZeRO-Infinity's NVMe bandwidth claim (arXiv
2104.07857) rests on aligned direct I/O — bytes-on-device, not
bytes-into-page-cache. With ``o_direct=True`` the handle opens swap files
with ``O_DIRECT`` and routes every submission through an alignment layer:

- a pooled :class:`AlignedArena` of anonymous-mmap buffers (page-aligned
  by construction, reused across submissions keyed by aligned capacity —
  steady state allocates nothing);
- callers whose buffers are already page-aligned with aligned lengths
  submit zero-copy; an aligned body + unaligned tail submits the body
  zero-copy and rides the tail through a one-page bounce buffer as a
  single aligned rewrite; fully unaligned buffers bounce whole;
- direct submissions are chunked Python-side at ``block_size``
  granularity so the C splitter (``submit_split``'s ceil division, which
  does NOT preserve alignment) always sees single-piece transfers;
- per-handle ``swap/device_read_mb_s`` / ``swap/device_write_mb_s``
  gauges measured submit→drain against direct bytes only (the buffered
  path's numbers would be cache-assisted, i.e. the lie this mode ends);
- a latched one-shot fallback to buffered I/O when the filesystem
  rejects O_DIRECT (tmpfs/overlayfs: EINVAL at open or at the write
  probe): one process-wide warning + a ``swap/o_direct_fallback``
  counter + flight-recorder breadcrumb, then every handle degrades to
  the buffered path — CI boxes degrade loudly instead of failing.

Direct-mode contract: file offsets must be page-aligned (every swap-tier
call site writes whole files at offset 0) and files written under
O_DIRECT have physical sizes rounded up to the page — byte-exact lengths
live in the swapper's ``meta``, and readers request the aligned length.
This module must stay importable without jax (ci/swap_gate.sh pins it).
"""

import ctypes
import errno
import fcntl
import mmap
import os
import threading
import time

import numpy as np

from deepspeed_tpu.ops.native.builder import AsyncIOBuilder
from deepspeed_tpu.utils.logging import logger

_lib = None

ALIGNMENT = mmap.PAGESIZE   # 4096 everywhere we run; safe for any FS
                            # logical block size (which divides the page)


def load():
    global _lib
    if _lib is None:
        lib = AsyncIOBuilder().load()
        lib.aio_handle_create.restype = ctypes.c_void_p
        lib.aio_handle_create.argtypes = [ctypes.c_int64, ctypes.c_int,
                                          ctypes.c_int, ctypes.c_int,
                                          ctypes.c_int]
        lib.aio_handle_create2.restype = ctypes.c_void_p
        lib.aio_handle_create2.argtypes = [ctypes.c_int64, ctypes.c_int,
                                           ctypes.c_int, ctypes.c_int,
                                           ctypes.c_int, ctypes.c_int]
        lib.aio_handle_backend.argtypes = [ctypes.c_void_p]
        lib.aio_handle_backend.restype = ctypes.c_int
        lib.aio_handle_destroy.argtypes = [ctypes.c_void_p]
        lib.aio_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.aio_open.restype = ctypes.c_int
        lib.aio_close.argtypes = [ctypes.c_int]
        for fn in (lib.aio_pread, lib.aio_pwrite):
            fn.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
                           ctypes.c_int64, ctypes.c_int64]
            fn.restype = None
        for fn in (lib.aio_sync_pread, lib.aio_sync_pwrite):
            fn.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
                           ctypes.c_int64, ctypes.c_int64]
            fn.restype = ctypes.c_int64
        lib.aio_handle_wait.argtypes = [ctypes.c_void_p]
        lib.aio_handle_wait.restype = ctypes.c_int64
        lib.aio_handle_errors.argtypes = [ctypes.c_void_p]
        lib.aio_handle_errors.restype = ctypes.c_int64
        _lib = lib
    return _lib


def align_up(n, alignment=ALIGNMENT):
    return -(-int(n) // alignment) * alignment


def aligned_empty(nbytes, alignment=ALIGNMENT):
    """Page-aligned uint8 array of exactly ``nbytes`` (capacity rounded
    up internally). Anonymous mmap is page-aligned by construction; the
    returned view keeps the mapping alive. For long-lived staging
    buffers — transient bounce buffers should lease from the arena."""
    mm = mmap.mmap(-1, max(align_up(nbytes, alignment), alignment))
    return np.frombuffer(mm, np.uint8)[:nbytes]


class _Lease:
    """One pooled aligned buffer, checked out of an AlignedArena."""

    __slots__ = ("arena", "mm", "cap", "view")

    def __init__(self, arena, mm, cap):
        self.arena = arena
        self.mm = mm
        self.cap = cap
        self.view = np.frombuffer(mm, np.uint8)

    def release(self):
        if self.arena is not None:
            self.arena._give(self.mm, self.cap)
            self.arena = None
            self.mm = None
            self.view = None


class AlignedArena:
    """Pooled page-aligned bounce buffers for O_DIRECT submissions.

    Buffers are anonymous ``mmap.mmap`` regions bucketed by aligned
    capacity; the swap tier's leaf sizes repeat every step, so after one
    cycle every lease is a free-list pop (steady state allocates
    nothing). Thread-safe: the read window and write-behind handles
    lease concurrently."""

    def __init__(self, alignment=ALIGNMENT):
        self.alignment = alignment
        self._free = {}          # capacity -> [mmap]
        self._lock = threading.Lock()
        self.allocated_bytes = 0  # total ever mmap'd (tests/telemetry)

    def lease(self, nbytes):
        cap = max(align_up(nbytes, self.alignment), self.alignment)
        with self._lock:
            bucket = self._free.get(cap)
            if bucket:
                mm = bucket.pop()
            else:
                mm = mmap.mmap(-1, cap)
                self.allocated_bytes += cap
        return _Lease(self, mm, cap)

    def _give(self, mm, cap):
        with self._lock:
            self._free.setdefault(cap, []).append(mm)


_ARENA = AlignedArena()


# -- the latched buffered fallback (module scope: one latch per process,
# -- shared by every handle — a box that rejects O_DIRECT rejects it for
# -- all of them) --------------------------------------------------------

_FALLBACK = {"latched": False, "warned": False}
_DIR_PROBE = {}   # abs dir -> bool (does this FS take O_DIRECT writes)
_FALLBACK_ERRNOS = (errno.EINVAL, errno.ENOTSUP,
                    getattr(errno, "EOPNOTSUPP", errno.ENOTSUP))


def o_direct_fallback_latched():
    return _FALLBACK["latched"]


def reset_o_direct_fallback_for_tests():
    """Clear the process-wide fallback latch + probe cache (tests flip
    between tmpfs and real-FS directories in one process)."""
    _FALLBACK["latched"] = False
    _FALLBACK["warned"] = False
    _DIR_PROBE.clear()


def _latch_fallback(path, err):
    _FALLBACK["latched"] = True
    try:
        from deepspeed_tpu.telemetry import default_recorder, \
            default_registry
        default_registry().counter("swap/o_direct_fallback").inc()
        if not _FALLBACK["warned"]:
            default_recorder().record("o_direct_fallback",
                                      path=str(path), error=str(err))
    except Exception:
        pass   # telemetry must never break the I/O path
    if not _FALLBACK["warned"]:
        _FALLBACK["warned"] = True
        logger.warning(
            "O_DIRECT unsupported on %s (%s) — latching the aio tier to "
            "BUFFERED I/O for this process; swap bandwidth numbers are "
            "page-cache-assisted from here on", path, err)


def _probe_o_direct(directory):
    """One direct write against a scratch file in ``directory`` — some
    filesystems accept the open flag and fail the first aligned pwrite
    (overlayfs generations), so EINVAL-at-open alone is not enough.
    Probe errors other than the rejection errnos report True (the real
    open will surface real errors: ENOSPC, EACCES...)."""
    d = os.path.abspath(directory)
    cached = _DIR_PROBE.get(d)
    if cached is not None:
        return cached
    probe = os.path.join(d, f".o_direct_probe.{os.getpid()}")
    ok = True
    fd = None
    lease = _ARENA.lease(ALIGNMENT)
    try:
        fd = os.open(probe, os.O_WRONLY | os.O_CREAT | os.O_TRUNC
                     | os.O_DIRECT, 0o644)
        os.pwrite(fd, lease.view[:ALIGNMENT].data, 0)
    except OSError as e:
        if e.errno in _FALLBACK_ERRNOS:
            ok = False
    finally:
        lease.release()
        if fd is not None:
            try:
                os.close(fd)
            except OSError:
                pass
        try:
            os.unlink(probe)
        except OSError:
            pass
    _DIR_PROBE[d] = ok
    return ok


def fd_is_direct(fd):
    """Authoritative per-fd answer (F_GETFL), no bookkeeping to rot when
    fds cross handles or get closed behind our back."""
    try:
        return bool(fcntl.fcntl(fd, fcntl.F_GETFL) & os.O_DIRECT)
    except OSError:
        return False


class AsyncIOHandle:
    """Python face of aio_handle_t (reference
    deepspeed_py_aio_handle.cpp:14-33): block_size/queue_depth/
    single_submit/overlap_events/thread_count knobs, async_pread/pwrite +
    wait. ``o_direct=True`` adds the direct-I/O alignment layer (module
    docstring); submissions against fds that were NOT opened O_DIRECT
    (checked per-fd) keep the buffered path even then."""

    def __init__(self, block_size=1048576, queue_depth=8, single_submit=False,
                 overlap_events=True, thread_count=1, backend="auto",
                 o_direct=False, registry=None):
        """``backend``: "auto" (io_uring when the kernel allows, else the
        thread pool), "threads", or "io_uring" (raises if unsupported)."""
        self.lib = load()
        self.block_size = block_size
        self.queue_depth = queue_depth
        self.single_submit = single_submit
        self.overlap_events = overlap_events
        self.thread_count = thread_count
        self.o_direct = bool(o_direct)
        self.alignment = ALIGNMENT
        # direct submissions are chunked here at block_size so the C
        # splitter never sub-divides one (its ceil-division pieces are
        # not alignment-preserving)
        self._chunk = max(align_up(block_size), ALIGNMENT)
        self._arena = _ARENA
        self._pending = []       # (kind, dst_view, lease, nbytes)
        self._win = {"r": [0, None], "w": [0, None]}  # bytes, t_first
        self._registry = registry
        self.stats = {"direct_zero_copy": 0, "direct_bounced": 0,
                      "direct_tail_bounced": 0}
        codes = {"auto": 0, "threads": 1, "io_uring": 2}
        if backend not in codes:
            raise ValueError(f"backend must be one of {sorted(codes)}, "
                             f"got {backend!r}")
        self._h = self.lib.aio_handle_create2(
            block_size, queue_depth, thread_count,
            int(single_submit), int(overlap_events), codes[backend])
        if not self._h:
            raise OSError("io_uring backend requested but unsupported by "
                          "this kernel/seccomp profile")

    @property
    def backend(self):
        return "io_uring" if self.lib.aio_handle_backend(self._h) else "threads"

    @property
    def direct_active(self):
        """Direct mode requested AND not latched to the fallback."""
        return self.o_direct and not _FALLBACK["latched"]

    def io_nbytes(self, nbytes):
        """Physical transfer/preallocation size for a leaf of ``nbytes``:
        aligned up under active O_DIRECT, byte-exact otherwise. Callers
        sizing staging buffers or swap files route through this so both
        modes share one code path."""
        return align_up(nbytes) if self.direct_active else int(nbytes)

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self.lib.aio_handle_destroy(self._h)
                self._h = None
        except Exception:
            pass

    # -- file helpers ------------------------------------------------------
    def open(self, path, for_write):
        if self.direct_active:
            flags = (os.O_WRONLY | os.O_CREAT | os.O_TRUNC) if for_write \
                else os.O_RDONLY
            fd = self._open_direct(path, flags)
            if fd is not None:
                return fd
        fd = self.lib.aio_open(str(path).encode(), int(for_write))
        if fd < 0:
            raise OSError(f"aio_open failed for {path}")
        return fd

    def open_fd(self, path, flags, mode=0o644):
        """os.open with the handle's direct mode applied — the swapper's
        custom-flag opens (no-O_TRUNC preallocated write fds) route here
        so every construction site shares the fallback latch."""
        if self.direct_active:
            fd = self._open_direct(path, flags, mode)
            if fd is not None:
                return fd
        return os.open(path, flags, mode)

    def _open_direct(self, path, flags, mode=0o644):
        """Try the O_DIRECT open; None means "latched, use buffered"."""
        directory = os.path.dirname(os.path.abspath(str(path))) or "."
        if not _probe_o_direct(directory):
            _latch_fallback(path, "probe write rejected")
            return None
        try:
            return os.open(str(path), flags | os.O_DIRECT, mode)
        except OSError as e:
            if e.errno in _FALLBACK_ERRNOS:
                _latch_fallback(path, e)
                return None
            raise

    def close(self, fd):
        # direct fds came from os.open; aio_close is a plain close(2)
        # wrapper, so one path serves both
        self.lib.aio_close(fd)

    @staticmethod
    def _buf(arr):
        assert isinstance(arr, np.ndarray) and arr.flags["C_CONTIGUOUS"]
        return arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes

    # -- async API (reference async_pread/async_pwrite + wait) -------------
    def async_pread(self, arr, fd, offset=0):
        if self.o_direct and fd_is_direct(fd):
            return self._direct_submit(arr, fd, offset, write=False)
        ptr, nbytes = self._buf(arr)
        self.lib.aio_pread(self._h, fd, ptr, nbytes, offset)

    def async_pwrite(self, arr, fd, offset=0):
        if self.o_direct and fd_is_direct(fd):
            return self._direct_submit(arr, fd, offset, write=True)
        ptr, nbytes = self._buf(arr)
        self.lib.aio_pwrite(self._h, fd, ptr, nbytes, offset)

    def wait(self):
        done = self.lib.aio_handle_wait(self._h)
        try:
            self._raise_errors()
        finally:
            self._drain_pending(failed=False)
        return done

    # -- direct-mode internals --------------------------------------------
    def _direct_submit(self, arr, fd, offset, write):
        assert isinstance(arr, np.ndarray) and arr.flags["C_CONTIGUOUS"]
        assert offset % self.alignment == 0, (
            f"O_DIRECT offsets must be {self.alignment}-aligned, "
            f"got {offset}")
        nbytes = arr.nbytes
        if nbytes == 0:
            return
        flat = arr.view(np.uint8).reshape(-1)
        a = self.alignment
        base_aligned = (arr.ctypes.data % a) == 0
        body = (nbytes // a) * a if base_aligned else 0
        tail = nbytes - body
        if body:
            self._submit_chunks(flat[:body], fd, offset, write)
            if tail == 0:
                self.stats["direct_zero_copy"] += 1
        if tail:
            # the unaligned remainder rides a pooled bounce buffer as a
            # single aligned transfer (zero-padded for writes — files
            # under O_DIRECT are aligned-size, exact lengths live in
            # the caller's metadata)
            bounce = align_up(tail)
            lease = self._arena.lease(bounce)
            if write:
                lease.view[:tail] = flat[body:]
                lease.view[tail:bounce] = 0
                self._submit_chunks(lease.view[:bounce], fd,
                                    offset + body, write)
                self._pending.append(("w", None, lease, 0))
            else:
                self._submit_chunks(lease.view[:bounce], fd,
                                    offset + body, write)
                self._pending.append(("r", flat[body:], lease, tail))
            self.stats["direct_tail_bounced" if body
                       else "direct_bounced"] += 1

    def _submit_chunks(self, view, fd, offset, write):
        """Aligned view → per-block_size C submissions (pieces==1 in the
        C layer, so its splitter cannot break alignment)."""
        nbytes = view.nbytes
        win = self._win["w" if write else "r"]
        if win[1] is None:
            win[1] = time.perf_counter()
        win[0] += nbytes
        submit = self.lib.aio_pwrite if write else self.lib.aio_pread
        for off in range(0, nbytes, self._chunk):
            chunk = view[off:off + min(self._chunk, nbytes - off)]
            ptr = chunk.ctypes.data_as(ctypes.c_void_p)
            submit(self._h, fd, ptr, chunk.nbytes, offset + off)

    def _drain_pending(self, failed):
        for kind, dst, lease, nbytes in self._pending:
            try:
                if kind == "r" and not failed:
                    dst[:] = lease.view[:nbytes]
            finally:
                lease.release()
        self._pending = []
        now = time.perf_counter()
        for direction, name in (("r", "swap/device_read_mb_s"),
                                ("w", "swap/device_write_mb_s")):
            nbytes, t0 = self._win[direction]
            if nbytes and t0 is not None and now > t0:
                self._gauge(name, nbytes / (now - t0) / 2**20)
            self._win[direction] = [0, None]

    def _gauge(self, name, mb_s):
        try:
            if self._registry is None:
                from deepspeed_tpu.telemetry import default_registry
                self._registry = default_registry()
            self._registry.gauge(name).set(round(mb_s, 1))
        except Exception:
            pass   # telemetry must never break the I/O path

    def _raise_errors(self):
        # aio_handle_errors returns-and-clears, so a failure is reported once
        # (to the wait that observed it) and does not poison later batches
        n = self.lib.aio_handle_errors(self._h)
        if n:
            self._drain_pending(failed=True)
            raise IOError(f"{n} async IO request(s) failed")

    # -- sync API (reference sync_pread/sync_pwrite) ------------------------
    def sync_pread(self, arr, path_or_fd, offset=0):
        fd, opened = self._fd(path_or_fd, False)
        try:
            if self.o_direct and fd_is_direct(fd):
                # the C sync calls bypass the alignment layer — route
                # direct fds through submit + drain (callers hold the
                # no-other-inflight-ops invariant already: sync ops on a
                # shared handle would absorb foreign completions)
                self._direct_submit(arr, fd, offset, write=False)
                self.wait()
                return arr.nbytes
            ptr, nbytes = self._buf(arr)
            done = self.lib.aio_sync_pread(self._h, fd, ptr, nbytes, offset)
            self._raise_errors()
            return done
        finally:
            if opened:
                self.close(fd)

    def sync_pwrite(self, arr, path_or_fd, offset=0):
        fd, opened = self._fd(path_or_fd, True)
        try:
            if self.o_direct and fd_is_direct(fd):
                self._direct_submit(arr, fd, offset, write=True)
                self.wait()
                return arr.nbytes
            ptr, nbytes = self._buf(arr)
            done = self.lib.aio_sync_pwrite(self._h, fd, ptr, nbytes, offset)
            self._raise_errors()
            return done
        finally:
            if opened:
                self.close(fd)

    def _fd(self, path_or_fd, for_write):
        if isinstance(path_or_fd, int):
            return path_or_fd, False
        return self.open(path_or_fd, for_write), True
