"""Native op build system — rebuild of op_builder/builder.py:81,205,217.

The reference JIT-compiles CUDA extensions through torch's ninja wrapper;
here each op is a plain C++ shared library compiled with g++ straight from
deepspeed_tpu/csrc/, cached next to the sources, and loaded with ctypes.
No nvcc, no compute-capability matrix — the TPU compute path is Pallas; this
covers host-side ops (SIMD optimizer, async IO).
"""

import ctypes
import os
import subprocess
import threading

from deepspeed_tpu.utils.logging import logger

CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "csrc")
BUILD_DIR = os.path.join(CSRC, "build")

_lock = threading.Lock()
_cache = {}


class OpBuilder:
    """One source → one .so. ``load()`` compiles on first use (the
    reference's jit_load path, builder.py:217) and returns the ctypes CDLL.
    """

    def __init__(self, name, sources, extra_flags=()):
        self.name = name
        self.sources = sources
        self.extra_flags = list(extra_flags)

    def absolute_sources(self):
        return [os.path.join(CSRC, s) for s in self.sources]

    def so_path(self):
        suffix = "_tsan" if self._tsan() else ""
        return os.path.join(BUILD_DIR, f"lib{self.name}{suffix}.so")

    @staticmethod
    def _tsan():
        """DS_BUILD_TSAN=1 builds the host libraries under ThreadSanitizer —
        the concurrency guard rail SURVEY §5.2 calls for on the swap/aio
        thread pools (the reference has no sanitizer story at all). TSAN
        builds cache separately so switching modes doesn't thrash.

        Running requires the runtime preloaded (dlopen'ing a TSAN .so into
        a plain python hits the static-TLS limit):

            LD_PRELOAD=$(g++ -print-file-name=libtsan.so) \\
                DS_BUILD_TSAN=1 python -m pytest tests/test_offload.py
        """
        return os.environ.get("DS_BUILD_TSAN", "") == "1"

    def is_compatible(self):
        from shutil import which
        return which("g++") is not None

    def command(self):
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
               "-march=native", "-fopenmp"]
        if self._tsan():
            cmd += ["-fsanitize=thread", "-g", "-O1"]
        return (cmd + self.extra_flags + self.absolute_sources()
                + ["-o", self.so_path()])

    def needs_build(self):
        so = self.so_path()
        if not os.path.exists(so):
            return True
        so_mtime = os.path.getmtime(so)
        return any(os.path.getmtime(s) > so_mtime
                   for s in self.absolute_sources())

    def build(self):
        os.makedirs(BUILD_DIR, exist_ok=True)
        cmd = self.command()
        logger.info(f"[op_builder] building {self.name}: {' '.join(cmd)}")
        try:
            subprocess.check_output(cmd, stderr=subprocess.STDOUT)
        except subprocess.CalledProcessError as e:
            # retry without -march=native (portable fallback)
            cmd = [c for c in cmd if c != "-march=native"]
            try:
                subprocess.check_output(cmd, stderr=subprocess.STDOUT)
            except subprocess.CalledProcessError as e2:
                raise RuntimeError(
                    f"failed to build {self.name}: {e2.output.decode()}") from e

    def load(self):
        with _lock:
            if self.name in _cache:
                return _cache[self.name]
            if not self.is_compatible():
                raise RuntimeError("no C++ compiler available")
            if self.needs_build():
                self.build()
            lib = ctypes.CDLL(self.so_path())
            _cache[self.name] = lib
            return lib


class CPUAdamBuilder(OpBuilder):
    def __init__(self):
        super().__init__("cpu_adam", ["cpu_adam.cpp"])


class AsyncIOBuilder(OpBuilder):
    def __init__(self):
        super().__init__("aio", ["aio.cpp"], extra_flags=["-pthread"])


ALL_OPS = {
    "cpu_adam": CPUAdamBuilder,
    "async_io": AsyncIOBuilder,
}
