"""ctypes binding for the SIMD CPU Adam library (csrc/cpu_adam.cpp) —
the reference's pybind layer (csrc/adam/cpu_adam.cpp:684-689) equivalent."""

import ctypes

import numpy as np

from deepspeed_tpu.ops.native.builder import CPUAdamBuilder

_lib = None


_F32P = ctypes.POINTER(ctypes.c_float)
_U16P = ctypes.POINTER(ctypes.c_uint16)
_I64P = ctypes.POINTER(ctypes.c_int64)


def _check(*arrays, dtype=np.float32):
    for arr in arrays:
        assert isinstance(arr, np.ndarray) and arr.dtype == dtype \
            and arr.flags["C_CONTIGUOUS"], f"need contiguous {dtype} arrays"


class _NativeCpuAdam:
    def __init__(self, lib):
        self.lib = lib
        lib.ds_adam_step.argtypes = [
            _F32P, _F32P, _F32P, _F32P,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int, ctypes.c_int]
        lib.ds_adam_step.restype = None
        lib.ds_adam_step_multi.argtypes = [
            ctypes.POINTER(_F32P), ctypes.POINTER(_F32P),
            ctypes.POINTER(_F32P), ctypes.POINTER(_F32P), _I64P,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int, ctypes.c_int]
        lib.ds_adam_step_multi.restype = None
        lib.ds_lamb_step.argtypes = [
            _F32P, _F32P, _F32P, _F32P, _F32P,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_int]
        lib.ds_lamb_step.restype = None
        lib.ds_adam_step_ex.argtypes = [
            _F32P, ctypes.c_void_p, ctypes.c_int, ctypes.c_float,
            _F32P, _F32P, _U16P,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int, ctypes.c_int]
        lib.ds_adam_step_ex.restype = None
        lib.ds_lamb_step_ex.argtypes = [
            _F32P, ctypes.c_void_p, ctypes.c_int, ctypes.c_float,
            _F32P, _F32P, _F32P, _U16P,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_int]
        lib.ds_lamb_step_ex.restype = None
        lib.ds_fp32_to_bf16.argtypes = [_F32P, _U16P, ctypes.c_int64]
        lib.ds_bf16_to_fp32.argtypes = [_U16P, _F32P, ctypes.c_int64]
        lib.ds_l2_norm_sq.argtypes = [_F32P, ctypes.c_int64]
        lib.ds_l2_norm_sq.restype = ctypes.c_double
        lib.ds_adam_num_threads.restype = ctypes.c_int

    def adam_step(self, params, grads, exp_avg, exp_avg_sq, step, lr,
                  beta1, beta2, eps, weight_decay, adamw_mode,
                  bias_correction=True):
        _check(params, grads, exp_avg, exp_avg_sq)
        self.lib.ds_adam_step(
            params.ctypes.data_as(_F32P), grads.ctypes.data_as(_F32P),
            exp_avg.ctypes.data_as(_F32P), exp_avg_sq.ctypes.data_as(_F32P),
            params.size, int(step), float(lr), float(beta1), float(beta2),
            float(eps), float(weight_decay), int(bool(adamw_mode)),
            int(bool(bias_correction)))

    def adam_step_multi(self, params, grads, exp_avg, exp_avg_sq, step, lr,
                        beta1, beta2, eps, weight_decay, adamw_mode,
                        bias_correction=True):
        """One call for a whole leaf list (reference multi-tensor apply)."""
        n = len(params)
        assert n == len(grads) == len(exp_avg) == len(exp_avg_sq)
        for group in (params, grads, exp_avg, exp_avg_sq):
            _check(*group)

        def ptr_array(group):
            return (_F32P * n)(*(a.ctypes.data_as(_F32P) for a in group))

        sizes = (ctypes.c_int64 * n)(*(a.size for a in params))
        self.lib.ds_adam_step_multi(
            ptr_array(params), ptr_array(grads), ptr_array(exp_avg),
            ptr_array(exp_avg_sq), sizes, n, int(step), float(lr),
            float(beta1), float(beta2), float(eps), float(weight_decay),
            int(bool(adamw_mode)), int(bool(bias_correction)))

    @staticmethod
    def _grad_ptr(grads):
        """(void* ptr, is_bf16) for fp32 or bf16(-as-uint16/ml_dtypes) grads."""
        assert isinstance(grads, np.ndarray) and grads.flags["C_CONTIGUOUS"]
        if grads.dtype == np.float32:
            return ctypes.c_void_p(grads.ctypes.data), 0
        if grads.dtype == np.uint16 or grads.dtype.name == "bfloat16":
            return ctypes.c_void_p(grads.ctypes.data), 1
        # float16 has itemsize 2 too but its bits are NOT bf16 — widen first
        raise TypeError(f"grads must be fp32 or bf16, got {grads.dtype}")

    def adam_step_ex(self, params, grads, exp_avg, exp_avg_sq, step, lr,
                     beta1, beta2, eps, weight_decay, adamw_mode,
                     bias_correction=True, grad_scale=1.0, params_bf16=None):
        """Single-pass step: grads read in wire dtype (fp32 or bf16 bits)
        scaled by ``grad_scale``; optional bf16 copy of the updated params
        written to ``params_bf16`` (uint16 bits) for the device push."""
        _check(params, exp_avg, exp_avg_sq)
        gptr, gbf16 = self._grad_ptr(grads)
        out = None
        if params_bf16 is not None:
            _check(params_bf16, dtype=np.uint16)
            out = params_bf16.ctypes.data_as(_U16P)
        self.lib.ds_adam_step_ex(
            params.ctypes.data_as(_F32P), gptr, gbf16, float(grad_scale),
            exp_avg.ctypes.data_as(_F32P), exp_avg_sq.ctypes.data_as(_F32P),
            out, params.size, int(step), float(lr), float(beta1),
            float(beta2), float(eps), float(weight_decay),
            int(bool(adamw_mode)), int(bool(bias_correction)))

    def lamb_step_ex(self, params, grads, exp_avg, exp_avg_sq, step, lr,
                     beta1, beta2, eps, weight_decay, max_coeff, min_coeff,
                     bias_correction=True, grad_scale=1.0, params_bf16=None,
                     update_buf=None):
        _check(params, exp_avg, exp_avg_sq)
        gptr, gbf16 = self._grad_ptr(grads)
        if update_buf is None:
            update_buf = np.empty_like(params)
        out = None
        if params_bf16 is not None:
            _check(params_bf16, dtype=np.uint16)
            out = params_bf16.ctypes.data_as(_U16P)
        self.lib.ds_lamb_step_ex(
            params.ctypes.data_as(_F32P), gptr, gbf16, float(grad_scale),
            exp_avg.ctypes.data_as(_F32P), exp_avg_sq.ctypes.data_as(_F32P),
            update_buf.ctypes.data_as(_F32P), out,
            params.size, int(step), float(lr), float(beta1), float(beta2),
            float(eps), float(weight_decay), float(max_coeff),
            float(min_coeff), int(bool(bias_correction)))

    def lamb_step(self, params, grads, exp_avg, exp_avg_sq, step, lr,
                  beta1, beta2, eps, weight_decay, max_coeff, min_coeff,
                  bias_correction=True, update_buf=None):
        _check(params, grads, exp_avg, exp_avg_sq)
        if update_buf is None:
            update_buf = np.empty_like(params)
        self.lib.ds_lamb_step(
            params.ctypes.data_as(_F32P), grads.ctypes.data_as(_F32P),
            exp_avg.ctypes.data_as(_F32P), exp_avg_sq.ctypes.data_as(_F32P),
            update_buf.ctypes.data_as(_F32P),
            params.size, int(step), float(lr), float(beta1), float(beta2),
            float(eps), float(weight_decay), float(max_coeff),
            float(min_coeff), int(bool(bias_correction)))

    def fp32_to_bf16(self, src, dst=None):
        _check(src)
        if dst is None:
            dst = np.empty(src.shape, np.uint16)
        _check(dst, dtype=np.uint16)
        self.lib.ds_fp32_to_bf16(src.ctypes.data_as(_F32P),
                                 dst.ctypes.data_as(_U16P), src.size)
        return dst

    def bf16_to_fp32(self, src, dst=None):
        _check(src, dtype=np.uint16)
        if dst is None:
            dst = np.empty(src.shape, np.float32)
        _check(dst)
        self.lib.ds_bf16_to_fp32(src.ctypes.data_as(_U16P),
                                 dst.ctypes.data_as(_F32P), src.size)
        return dst

    def l2_norm(self, arr):
        _check(arr)
        import math
        return math.sqrt(self.lib.ds_l2_norm_sq(
            arr.ctypes.data_as(_F32P), arr.size))

    def num_threads(self):
        return self.lib.ds_adam_num_threads()


def load():
    """Build (if needed) + load the native library; returns the wrapper or
    raises on toolchain absence."""
    global _lib
    if _lib is None:
        _lib = _NativeCpuAdam(CPUAdamBuilder().load())
    return _lib
