"""ctypes binding for the SIMD CPU Adam library (csrc/cpu_adam.cpp) —
the reference's pybind layer (csrc/adam/cpu_adam.cpp:684-689) equivalent."""

import ctypes

import numpy as np

from deepspeed_tpu.ops.native.builder import CPUAdamBuilder

_lib = None


class _NativeCpuAdam:
    def __init__(self, lib):
        self.lib = lib
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.ds_adam_step.argtypes = [
            f32p, f32p, f32p, f32p,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int, ctypes.c_int]
        lib.ds_adam_step.restype = None
        lib.ds_adam_num_threads.restype = ctypes.c_int

    def adam_step(self, params, grads, exp_avg, exp_avg_sq, step, lr,
                  beta1, beta2, eps, weight_decay, adamw_mode,
                  bias_correction=True):
        for arr in (params, grads, exp_avg, exp_avg_sq):
            assert isinstance(arr, np.ndarray) and arr.dtype == np.float32 \
                and arr.flags["C_CONTIGUOUS"], "need contiguous fp32 arrays"
        n = params.size
        f32p = ctypes.POINTER(ctypes.c_float)
        self.lib.ds_adam_step(
            params.ctypes.data_as(f32p), grads.ctypes.data_as(f32p),
            exp_avg.ctypes.data_as(f32p), exp_avg_sq.ctypes.data_as(f32p),
            n, int(step), float(lr), float(beta1), float(beta2), float(eps),
            float(weight_decay), int(bool(adamw_mode)),
            int(bool(bias_correction)))

    def num_threads(self):
        return self.lib.ds_adam_num_threads()


def load():
    """Build (if needed) + load the native library; returns the wrapper or
    raises on toolchain absence."""
    global _lib
    if _lib is None:
        _lib = _NativeCpuAdam(CPUAdamBuilder().load())
    return _lib
