"""Sparse-attention model integration — rebuild of the reference's
ops/sparse_attention/sparse_attention_utils.py (SparseAttentionUtils) and
bert_sparse_self_attention.py (BertSparseSelfAttention).

The reference surgically swaps `nn.Module` attention objects inside a live
HF BERT/RoBERTa model (replace_model_self_attention_with_sparse_self_attention)
and patches position-embedding tensors in place. Flax models are config-
driven and parameters are explicit pytrees, so the TPU equivalents are:

  * a `BertSparseSelfAttention` flax module usable as the attention block
    of an encoder layer;
  * config rewriting (`sparse_config_for`) instead of object surgery;
  * pure-function helpers over parameter pytrees / batch arrays for the
    position-embedding extension and block-size padding.
"""

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    SparseSelfAttention,
    sparse_attention,
)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    SparsityConfig,
    FixedSparsityConfig,
)


class BertSparseSelfAttention(nn.Module):
    """BERT self-attention block computing QKV then block-sparse attention
    (reference bert_sparse_self_attention.py:9). Drop-in for the dense
    attention inside a BERT encoder layer: [B, S, E] → [B, S, E] context
    (before the output projection)."""
    hidden_size: int
    num_attention_heads: int
    sparsity_config: SparsityConfig
    dtype: any = jnp.bfloat16
    param_dtype: any = jnp.float32
    initializer_range: float = 0.02

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None):
        E = self.hidden_size
        H = self.num_attention_heads
        assert E % H == 0
        B, S, _ = hidden_states.shape
        init = nn.initializers.normal(self.initializer_range)
        qkv = nn.Dense(3 * E, dtype=self.dtype, param_dtype=self.param_dtype,
                       kernel_init=init, name="qkv")(hidden_states)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, S, H, E // H).transpose(0, 2, 1, 3)

        op = SparseSelfAttention(self.sparsity_config)
        ctx = op(heads(q), heads(k), heads(v),
                 key_padding_mask=attention_mask)
        return ctx.transpose(0, 2, 1, 3).reshape(B, S, E)


class SparseAttentionUtils:
    """Helpers mirroring the reference SparseAttentionUtils API."""

    @staticmethod
    def extend_position_embedding(params, max_position):
        """Return a params pytree whose position-embedding table is extended
        to ``max_position`` rows by tiling the learned table (the reference
        repeats the original weights, sparse_attention_utils.py:52-80:
        'this is a temporary hack'; it keeps the embedding distribution).

        Works on any pytree containing a leaf whose path ends in
        'position_embeddings' (our BertModel) or 'wpe' (our GPT-2)."""
        def maybe_extend(path, leaf):
            names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
            if not names:
                return leaf
            if names[-1] not in ("position_embeddings", "wpe"):
                return leaf
            orig, width = leaf.shape
            if max_position <= orig:
                return leaf
            reps = int(np.ceil(max_position / orig))
            return jnp.tile(leaf, (reps, 1))[:max_position]

        return jax.tree_util.tree_map_with_path(maybe_extend, params)

    @staticmethod
    def update_tokenizer_model_max_length(tokenizer, max_position):
        """Parity helper (reference :82-96): bump a HF-style tokenizer's
        max length so it can emit extended sequences."""
        tokenizer.model_max_length = max_position
        if hasattr(tokenizer, "init_kwargs"):
            tokenizer.init_kwargs["model_max_length"] = max_position
        return tokenizer

    @staticmethod
    def sparse_config_for(bert_config, sparsity_config=None):
        """Config rewriting in place of the reference's module surgery
        (replace_model_self_attention_with_sparse_self_attention, :98-153):
        returns a copy of our BertConfig with the sparse layout attached
        (the encoder layer reads it and routes attention through the
        block-sparse kernel)."""
        import dataclasses
        sparsity_config = sparsity_config or FixedSparsityConfig(
            num_heads=bert_config.num_attention_heads)
        return dataclasses.replace(bert_config,
                                   sparsity_config=sparsity_config)

    @staticmethod
    def pad_to_block_size(block_size, input_ids=None, attention_mask=None,
                          token_type_ids=None, position_ids=None,
                          inputs_embeds=None, pad_token_id=0,
                          model_embeddings=None):
        """Pad sequence-dim inputs up to a multiple of the sparsity block
        (reference :155-211). Returns (pad_len, padded tensors in the same
        order). ``model_embeddings`` is accepted for signature parity and
        unused (flax embeds inside the model)."""
        seqs = [t for t in (input_ids, attention_mask, token_type_ids,
                            position_ids, inputs_embeds) if t is not None]
        assert seqs, "nothing to pad"
        S = seqs[0].shape[1]
        pad_len = (block_size - S % block_size) % block_size

        def pad(t, value=0):
            if t is None or pad_len == 0:
                return t
            widths = [(0, 0), (0, pad_len)] + [(0, 0)] * (t.ndim - 2)
            return jnp.pad(t, widths, constant_values=value)

        return (pad_len,
                pad(input_ids, pad_token_id),
                pad(attention_mask, 0),       # padded keys masked out
                pad(token_type_ids, 0),
                pad(position_ids, 0),
                pad(inputs_embeds, 0))

    @staticmethod
    def unpad_sequence_output(pad_len, sequence_output):
        """Strip the block padding from the model output (reference
        :213-222)."""
        if pad_len:
            return sequence_output[:, :-pad_len]
        return sequence_output
