"""Block-sparse self-attention op.

Rebuild of deepspeed/ops/sparse_attention/sparse_self_attention.py:14: QK^T /
softmax / PV restricted to a block layout. The reference lowers to Triton
SDD/DSD/DDS block matmuls (matmul.py:16) + block softmax (softmax.py:17); on
TPU we lower to the Pallas block-sparse kernel
(deepspeed_tpu/ops/pallas/blocksparse.py) when running on TPU, and to an
XLA dense-with-mask fallback elsewhere (tests, CPU). Both paths compute
identical numerics: softmax over only the blocks present in the layout.
"""

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    SparsityConfig, FixedSparsityConfig)


def _expand_layout_mask(layout, block, seq_len):
    """[H, nb, nb] 0/1 block layout → [H, S, S] boolean element mask."""
    nb = seq_len // block
    layout = np.asarray(layout)[:, :nb, :nb]
    mask = np.repeat(np.repeat(layout, block, axis=1), block, axis=2)
    return jnp.asarray(mask.astype(bool))


def _kernel_beats_dense(layout, block, S):
    """v5e-calibrated crossover: the streaming kernel is DMA-ISSUE bound
    (~1.4 us per tile copy measured round 4 — compute is ~2% of its
    runtime), so its cost scales with the ACTIVE BLOCK COUNT, while the
    masked-dense einsum path scales with S^2 (and runs at roughly 0.4x of
    dense flash's efficiency: unfused softmax + full score
    materialization). Comparing the two estimates:

        kernel  ~ 3 passes x active_pairs x 1.4 us (per B*H)
        dense   ~ S^2 work at the measured einsum rate

    the kernel loses only when the layout is nearly full. Measured sweep
    (tests/perf/blocksparse_sweep.py, fwd+bwd vs dense FLASH): S=4096
    block 128/256/512 -> 0.82x/0.92x/1.25x at density .23/.43/.73;
    S=16384 -> 2.04x/2.78x/2.36x at density .06/.12/.23. The masked
    einsum is ~2.5x slower than flash, so the kernel wins vs the
    semantics-preserving dense path at every measured point; this
    predicate only rejects near-dense layouts where block count
    approaches (S/block)^2."""
    nb = S // block
    density = float(np.asarray(layout)[:, :nb, :nb].mean())
    # per-(B*H) estimates: 3 kernel passes (fwd, dq, dkv) x issue rate;
    # masked einsum ~2.5x the measured dense-flash rate of
    # 0.64 ms / (B*H) at S=4096 => 9.5e-5 us per score element
    kernel_us = 3 * density * nb * nb * 1.4
    einsum_us = 9.5e-5 * S * S
    return kernel_us < einsum_us


def _dense_path_fits(layout, S, n_heads, batch):
    """The masked-dense path materializes [B, H, S, S] scores (bf16 + an
    fp32 softmax copy) — never send kernel-scale sequences there on a
    time estimate alone; a slower kernel beats an OOM."""
    return batch * n_heads * S * S * 6 < 2 << 30


def sparse_attention(q, k, v, layout, block, key_padding_mask=None,
                     attn_mask=None, scale=None, use_kernel=None):
    """Masked attention with a static block-sparse layout.

    q/k/v: [B, H, S, D]. layout: [H, S//block, S//block] ndarray.
    Returns [B, H, S, D]. Differentiable on both paths (the Pallas kernel
    carries a custom VJP — trainable like the reference's Triton op).
    use_kernel: None = auto — the kernel on TPU unless the calibrated
    crossover predicts the masked-dense path is faster for this layout
    (near-full layouts; see _kernel_beats_dense), dense fallback off-TPU;
    True forces the kernel (interpret mode off-TPU — how CI exercises it).
    """
    B, H, S, D = q.shape
    scale = scale if scale is not None else 1.0 / np.sqrt(D)

    from deepspeed_tpu.utils.platform import is_tpu_backend
    if use_kernel is None:
        use_pallas = is_tpu_backend() and (
            _kernel_beats_dense(layout, block, S)
            or not _dense_path_fits(layout, S, H, B))
    else:
        use_pallas = use_kernel
    if use_pallas:
        try:
            from deepspeed_tpu.ops.pallas.blocksparse import blocksparse_attention
            return blocksparse_attention(q, k, v, np.asarray(layout), block,
                                         scale=scale,
                                         key_padding_mask=key_padding_mask,
                                         attn_mask=attn_mask)
        except NotImplementedError:
            if use_kernel:
                raise

    # dense fallback only: the [H, S, S] element mask is hundreds of MB at
    # kernel-scale sequence lengths, so build it after kernel dispatch
    mask = _expand_layout_mask(layout, block, S)

    scores = jnp.einsum("bhsd,bhtd->bhst", q, k) * scale
    neg = jnp.finfo(scores.dtype).min
    scores = jnp.where(mask[None], scores, neg)
    if attn_mask is not None:
        scores = jnp.where(attn_mask.astype(bool), scores, neg)
    if key_padding_mask is not None:
        scores = jnp.where(key_padding_mask[:, None, None, :].astype(bool), scores, neg)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    # rows with no allowed keys produce uniform junk; zero them like the
    # reference's block softmax (absent rows never contribute)
    any_allowed = mask.any(axis=-1)[None, :, :, None]
    probs = jnp.where(any_allowed, probs, 0.0)
    return jnp.einsum("bhst,bhtd->bhsd", probs, v)


class SparseSelfAttention:
    """Module-style wrapper mirroring the reference class
    (sparse_self_attention.py:14): holds a SparsityConfig, caches layouts per
    sequence length, applies sparse attention to [B, H, S, D] q/k/v."""

    def __init__(self, sparsity_config=None, key_padding_mask_mode="add",
                 attn_mask_mode="mul", max_seq_length=2048):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(num_heads=4)
        assert isinstance(self.sparsity_config, SparsityConfig)
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self._layout_cache = {}

    def get_layout(self, seq_len):
        if seq_len not in self._layout_cache:
            self._layout_cache[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layout_cache[seq_len]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None,
                 attn_mask=None):
        assert query.dtype in (jnp.float32, jnp.bfloat16, jnp.float16), (
            "sparse attention supports float dtypes")
        S = query.shape[-2]
        layout = self.get_layout(S)
        # "add" mask mode means additive -inf masks in the reference; we accept
        # boolean masks and treat mode only for parity bookkeeping.
        return sparse_attention(query, key, value, layout,
                                self.sparsity_config.block,
                                key_padding_mask=key_padding_mask,
                                attn_mask=attn_mask)
