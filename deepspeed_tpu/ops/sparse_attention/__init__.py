from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    SparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    VariableSparsityConfig,
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import SparseSelfAttention
