"""Block-sparse attention layout generators.

Behavioral rebuild of the reference's layout family
(deepspeed/ops/sparse_attention/sparsity_config.py:94 Fixed, :243 Variable,
:421 BigBird, :544 BSLongformer) producing `[num_heads, num_blocks,
num_blocks]` 0/1 layouts consumed by the Pallas block-sparse kernels
(deepspeed_tpu/ops/pallas/blocksparse.py).

Construction is vectorized numpy: every pattern is the union of a few
boolean component masks built from index arithmetic over the block grid —
a same-window equivalence mask for local attention, a banded mask for
sliding windows, and row/column stripe masks for global attention — with
causality applied once as a final `np.tril`. (Building bidirectionally and
lower-triangling at the end is equivalent to the reference's per-loop
causal clipping: the intersection of any of these masks with the lower
triangle is the same either way.) Layouts are host-side static data baked
into the kernel grid at trace time.

TPU note: the reference's Triton kernels used block=16 defaults; on TPU the
MXU/VMEM tiling prefers block sizes that are multiples of 128 in the lane
dim, so `block` here defaults to 128 for kernel use, while any value is
legal for layout math (kept at 16 by the config-schema default for config
parity).
"""

import numpy as np


def _stripe(nb, indices=None, ranges=None):
    """Boolean [nb] vector marking global block positions, from either a
    list of single block indices (negative = from the end, numpy-style) or
    (start, end) ranges. Out-of-range entries are clipped/ignored."""
    cols = np.zeros(nb, dtype=bool)
    if ranges is not None:
        for start, end in ranges:
            cols[start:min(end, nb)] = True
    elif indices is not None:
        valid = [i for i in indices if -nb <= i < nb]
        cols[valid] = True
    return cols


def _same_window(window_ids):
    """[nb] window ids -> [nb, nb] mask of (row, col) in the same window."""
    return window_ids[:, None] == window_ids[None, :]


def _banded(nb, half_width):
    """[nb, nb] mask of |row - col| <= half_width (sliding window)."""
    idx = np.arange(nb)
    return np.abs(idx[:, None] - idx[None, :]) <= half_width


def _random_cols(nb, k):
    """[nb, nb] mask with k distinct random columns per row (vectorized:
    rank a random score matrix per row and keep the k smallest)."""
    mask = np.zeros((nb, nb), dtype=bool)
    if k > 0:
        picks = np.argpartition(np.random.rand(nb, nb), k - 1, axis=1)[:, :k]
        mask[np.arange(nb)[:, None], picks] = True
    return mask


class SparsityConfig:
    """Base: holds head count, block size, per-head layout switch."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def _num_blocks(self, seq_len):
        if seq_len % self.block != 0:
            raise ValueError(
                f"Sequence length {seq_len} must be divisible by block size {self.block}")
        return seq_len // self.block

    def _head_mask(self, h, num_blocks):
        """Boolean [num_blocks, num_blocks] attention-block mask for head h."""
        raise NotImplementedError

    def make_layout(self, seq_len):
        nb = self._num_blocks(seq_len)
        heads = [self._head_mask(h, nb) for h in range(self.num_layout_heads)]
        heads.extend(heads[0] for _ in range(self.num_heads - len(heads)))
        return np.stack(heads).astype(np.int64)


class DenseSparsityConfig(SparsityConfig):
    """All-ones layout: lets the sparse kernel path run dense (reference
    sparsity_config.py:60-ish Dense class)."""

    def _head_mask(self, h, num_blocks):
        return np.ones((num_blocks, num_blocks), dtype=bool)


class FixedSparsityConfig(SparsityConfig):
    """'Fixed' pattern (Sparse Transformers, Child et al. 2019): local windows
    of `num_local_blocks`, plus global attention to a `num_global_blocks`-wide
    column slot inside each window; the slot offset rotates across head
    groups when `num_different_global_patterns` > 1, and rows of the same
    slots become global too under `horizontal_global_attention`."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_local_blocks=4,
                 num_global_blocks=1,
                 attention="bidirectional",
                 horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"Number of blocks in a local window ({num_local_blocks}) must be "
                f"dividable by number of global blocks ({num_global_blocks})")
        self.num_global_blocks = num_global_blocks
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only unidirectional or bidirectional attentions are supported")
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                "only bidirectional attention can support horizontal global attention")
        self.horizontal_global_attention = horizontal_global_attention
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "number of different global attentions is only valid if "
                "different layouts are generated per head")
        if num_different_global_patterns > (num_local_blocks // num_global_blocks):
            raise ValueError(
                f"Number of layout versions ({num_different_global_patterns}) cannot "
                f"be larger than number of local window blocks divided by number of "
                f"global blocks")
        self.num_different_global_patterns = num_different_global_patterns

    def _global_cols(self, h, num_blocks):
        """Boolean [nb] vector of global block-columns for head h: inside
        every complete window, the G-wide slot ending `pattern_index`
        slots from the window end; in an incomplete tail window, its last
        G columns."""
        L, G = self.num_local_blocks, self.num_global_blocks
        slot_start = L - (1 + h % self.num_different_global_patterns) * G
        idx = np.arange(num_blocks)
        phase = idx % L
        complete = num_blocks - num_blocks % L
        cols = (idx < complete) & (phase >= slot_start) & (phase < slot_start + G)
        if complete < num_blocks:
            cols |= idx >= max(complete, num_blocks - G)
        return cols

    def _head_mask(self, h, num_blocks):
        window_ids = np.arange(num_blocks) // self.num_local_blocks
        mask = _same_window(window_ids)
        gcols = self._global_cols(h, num_blocks)
        mask |= gcols[None, :]
        if self.horizontal_global_attention:
            mask |= gcols[:, None]
        if self.attention == "unidirectional":
            mask = np.tril(mask)
        return mask


class VariableSparsityConfig(SparsityConfig):
    """'Variable' pattern: random blocks + variable-size local windows +
    explicit global block indices/ranges (reference sparsity_config.py:243)."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_random_blocks=0,
                 local_window_blocks=(4,),
                 global_block_indices=(0,),
                 global_block_end_indices=None,
                 attention="bidirectional",
                 horizontal_global_attention=False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = list(local_window_blocks)
        self.global_block_indices = list(global_block_indices)
        if global_block_end_indices is not None:
            if len(global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    "global_block_indices and global_block_end_indices must have "
                    "the same length")
            for start, end in zip(global_block_indices, global_block_end_indices):
                if start >= end:
                    raise ValueError(
                        f"global block start index ({start}) must be smaller than "
                        f"its end index ({end})")
        self.global_block_end_indices = (list(global_block_end_indices)
                                         if global_block_end_indices is not None else None)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only unidirectional or bidirectional attentions are supported")
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                "only bidirectional attention can support horizontal global attention")
        self.horizontal_global_attention = horizontal_global_attention

    def _window_ids(self, num_blocks):
        """Assign each block a window id from the configured window sizes;
        the last size repeats to cover the rest of the sequence."""
        bounds = list(np.cumsum(self.local_window_blocks))
        tail = self.local_window_blocks[-1]
        while bounds[-1] < num_blocks:
            bounds.append(bounds[-1] + tail)
        return np.searchsorted(np.asarray(bounds), np.arange(num_blocks),
                               side="right")

    def _head_mask(self, h, num_blocks):
        if num_blocks < self.num_random_blocks:
            raise ValueError(
                f"Number of random blocks ({self.num_random_blocks}) must be smaller "
                f"than overall number of blocks in a row ({num_blocks})")
        mask = _random_cols(num_blocks, self.num_random_blocks)
        mask |= _same_window(self._window_ids(num_blocks))
        if self.global_block_end_indices is not None:
            gcols = _stripe(num_blocks, ranges=zip(self.global_block_indices,
                                                   self.global_block_end_indices))
        else:
            gcols = _stripe(num_blocks, indices=self.global_block_indices)
        mask |= gcols[None, :]
        if self.horizontal_global_attention:
            mask |= gcols[:, None]
        if self.attention == "unidirectional":
            mask = np.tril(mask)
        return mask


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird (Zaheer et al. 2020): random + sliding window + global
    first/last blocks (reference sparsity_config.py:421)."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_random_blocks=1,
                 num_sliding_window_blocks=3,
                 num_global_blocks=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks

    def _head_mask(self, h, num_blocks):
        for name, need in (("random", self.num_random_blocks),
                           ("sliding window", self.num_sliding_window_blocks),
                           ("global", self.num_global_blocks)):
            if num_blocks < need:
                raise ValueError(
                    f"Number of {name} blocks ({need}) must be smaller than "
                    f"overall number of blocks in a row ({num_blocks})")
        mask = _random_cols(num_blocks, self.num_random_blocks)
        mask |= _banded(num_blocks, self.num_sliding_window_blocks // 2)
        g = self.num_global_blocks
        edges = _stripe(num_blocks, ranges=[(0, g), (num_blocks - g, num_blocks)])
        mask |= edges[None, :]
        mask |= edges[:, None]
        return mask


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + explicit global block
    indices/ranges (reference sparsity_config.py:544)."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_sliding_window_blocks=3,
                 global_block_indices=(0,),
                 global_block_end_indices=None):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = list(global_block_indices)
        if global_block_end_indices is not None:
            if len(global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    "global_block_indices and global_block_end_indices must have "
                    "the same length")
            for start, end in zip(global_block_indices, global_block_end_indices):
                if start >= end:
                    raise ValueError(
                        f"global block start index ({start}) must be smaller than "
                        f"its end index ({end})")
        self.global_block_end_indices = (list(global_block_end_indices)
                                         if global_block_end_indices is not None else None)

    def _head_mask(self, h, num_blocks):
        if num_blocks < self.num_sliding_window_blocks:
            raise ValueError(
                f"Number of sliding window blocks ({self.num_sliding_window_blocks}) "
                f"must be smaller than overall number of blocks in a row ({num_blocks})")
        mask = _banded(num_blocks, self.num_sliding_window_blocks // 2)
        if self.global_block_end_indices is not None:
            g = _stripe(num_blocks, ranges=zip(self.global_block_indices,
                                               self.global_block_end_indices))
        else:
            g = _stripe(num_blocks, indices=self.global_block_indices)
        mask |= g[None, :]
        mask |= g[:, None]
        return mask


def config_to_sparsity(sa_config, num_heads):
    """Build a SparsityConfig from the json section
    (deepspeed_tpu/config/config.py SparseAttentionConfig) — the dispatch
    the reference does in config.py:236-406."""
    mode = sa_config.mode
    if mode == "dense":
        return DenseSparsityConfig(num_heads, sa_config.block,
                                   sa_config.different_layout_per_head)
    if mode == "fixed":
        return FixedSparsityConfig(
            num_heads, sa_config.block, sa_config.different_layout_per_head,
            sa_config.num_local_blocks, sa_config.num_global_blocks,
            sa_config.attention, sa_config.horizontal_global_attention,
            sa_config.num_different_global_patterns)
    if mode == "variable":
        return VariableSparsityConfig(
            num_heads, sa_config.block, sa_config.different_layout_per_head,
            sa_config.num_random_blocks, sa_config.local_window_blocks,
            sa_config.global_block_indices, sa_config.global_block_end_indices,
            sa_config.attention, sa_config.horizontal_global_attention)
    if mode == "bigbird":
        return BigBirdSparsityConfig(
            num_heads, sa_config.block, sa_config.different_layout_per_head,
            sa_config.num_random_blocks, sa_config.num_sliding_window_blocks,
            sa_config.num_global_blocks)
    if mode == "bslongformer":
        return BSLongformerSparsityConfig(
            num_heads, sa_config.block, sa_config.different_layout_per_head,
            sa_config.num_sliding_window_blocks, sa_config.global_block_indices,
            sa_config.global_block_end_indices)
    raise NotImplementedError(f"Given sparsity mode, {mode}, has not been implemented yet!")
