"""Block-sparse attention layout generators.

Behavioral rebuild of the reference's layout family
(deepspeed/ops/sparse_attention/sparsity_config.py:94 Fixed, :243 Variable,
:421 BigBird, :544 BSLongformer) producing `[num_heads, num_blocks,
num_blocks]` 0/1 layouts consumed by the Pallas block-sparse kernels
(deepspeed_tpu/ops/pallas/blocksparse.py). Implemented on numpy — layouts
are host-side static data baked into the kernel grid at trace time.

TPU note: the reference's Triton kernels used block=16 defaults; on TPU the
MXU/VMEM tiling prefers block sizes that are multiples of 128 in the lane
dim, so `block` here defaults to 128 for kernel use, while any value is legal
for layout math (kept at 16 by the config-schema default for config parity).
"""

import random

import numpy as np


class SparsityConfig:
    """Base: holds head count, block size, per-head layout switch."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len):
        if seq_len % self.block != 0:
            raise ValueError(
                f"Sequence length {seq_len} must be divisible by block size {self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), dtype=np.int64)

    def check_and_propagate_first_head_layout(self, layout):
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len):
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All-ones layout: lets the sparse kernel path run dense (reference
    sparsity_config.py:60-ish Dense class)."""

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:, :, :] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """'Fixed' pattern (Sparse Transformers, Child et al. 2019): local windows
    of `num_local_blocks`, plus global attention to the last
    `num_global_blocks` block-columns of each window; optionally different
    global offsets per head group and horizontal (row) global attention."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_local_blocks=4,
                 num_global_blocks=1,
                 attention="bidirectional",
                 horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"Number of blocks in a local window ({num_local_blocks}) must be "
                f"dividable by number of global blocks ({num_global_blocks})")
        self.num_global_blocks = num_global_blocks
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only unidirectional or bidirectional attentions are supported")
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                "only bidirectional attention can support horizontal global attention")
        self.horizontal_global_attention = horizontal_global_attention
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "number of different global attentions is only valid if "
                "different layouts are generated per head")
        if num_different_global_patterns > (num_local_blocks // num_global_blocks):
            raise ValueError(
                f"Number of layout versions ({num_different_global_patterns}) cannot "
                f"be larger than number of local window blocks divided by number of "
                f"global blocks")
        self.num_different_global_patterns = num_different_global_patterns

    def set_local_layout(self, h, layout):
        num_blocks = layout.shape[1]
        for i in range(0, num_blocks, self.num_local_blocks):
            end = min(i + self.num_local_blocks, num_blocks)
            for row in range(i, end):
                for col in range(i, (row + 1) if self.attention == "unidirectional" else end):
                    layout[h, row, col] = 1
        return layout

    def set_global_layout(self, h, layout):
        num_blocks = layout.shape[1]
        first_global_block_idx = (
            self.num_local_blocks - (1 + h % self.num_different_global_patterns)
            * self.num_global_blocks)
        # set all global blocks except the last one if (num_blocks % num_local_blocks) != 0
        end = num_blocks - (num_blocks % self.num_local_blocks)
        for i in range(first_global_block_idx, end, self.num_local_blocks):
            # vertical global attention
            first_row = 0 if self.attention == "bidirectional" else i
            # (((i // self.num_local_blocks) + 1) * self.num_local_blocks)
            layout[h, first_row:, i:i + self.num_global_blocks] = 1
            # horizontal global attention
            if self.horizontal_global_attention:
                layout[h, i:i + self.num_global_blocks, :] = 1
        # residue block-window shorter than num_local_blocks at the tail
        if end < num_blocks:
            start = max(end, num_blocks - self.num_global_blocks)
            first_row = 0 if self.attention == "bidirectional" else start
            layout[h, first_row:, start:] = 1
            if self.horizontal_global_attention:
                layout[h, start:, :] = 1
        if self.attention == "unidirectional":
            layout[h] = np.tril(layout[h])
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_local_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """'Variable' pattern: random blocks + variable-size local windows +
    explicit global block indices/ranges (reference sparsity_config.py:243)."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_random_blocks=0,
                 local_window_blocks=(4,),
                 global_block_indices=(0,),
                 global_block_end_indices=None,
                 attention="bidirectional",
                 horizontal_global_attention=False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = list(local_window_blocks)
        self.global_block_indices = list(global_block_indices)
        if global_block_end_indices is not None:
            if len(global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    "global_block_indices and global_block_end_indices must have "
                    "the same length")
            for start, end in zip(global_block_indices, global_block_end_indices):
                if start >= end:
                    raise ValueError(
                        f"global block start index ({start}) must be smaller than "
                        f"its end index ({end})")
        self.global_block_end_indices = (list(global_block_end_indices)
                                         if global_block_end_indices is not None else None)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only unidirectional or bidirectional attentions are supported")
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                "only bidirectional attention can support horizontal global attention")
        self.horizontal_global_attention = horizontal_global_attention

    def set_random_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_random_blocks:
            raise ValueError(
                f"Number of random blocks ({self.num_random_blocks}) must be smaller "
                f"than overall number of blocks in a row ({num_blocks})")
        for row in range(num_blocks):
            rnd_cols = random.sample(range(num_blocks), self.num_random_blocks)
            layout[h, row, rnd_cols] = 1
        return layout

    def set_local_layout(self, h, layout):
        num_blocks = layout.shape[1]
        start_block_idx = 0
        end_block_idx = 0
        for block_size in self.local_window_blocks:
            end_block_idx += block_size
            end_block_idx = min(end_block_idx, num_blocks)
            for row in range(start_block_idx, end_block_idx):
                for col in range(start_block_idx,
                                 (row + 1) if self.attention == "unidirectional"
                                 else end_block_idx):
                    layout[h, row, col] = 1
            start_block_idx += block_size
        # repeat the last window size for remaining blocks
        for i in range(start_block_idx, num_blocks, self.local_window_blocks[-1]):
            end_block_idx = min(i + self.local_window_blocks[-1], num_blocks)
            for row in range(i, end_block_idx):
                for col in range(i,
                                 (row + 1) if self.attention == "unidirectional"
                                 else end_block_idx):
                    layout[h, row, col] = 1
        return layout

    def set_global_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if self.global_block_end_indices is None:
            for idx in self.global_block_indices:
                if idx < num_blocks:
                    # vertical
                    first_row = 0 if self.attention == "bidirectional" else idx
                    layout[h, first_row:, idx] = 1
                    # horizontal
                    if self.horizontal_global_attention:
                        layout[h, idx, :] = 1
        else:
            for start, end in zip(self.global_block_indices, self.global_block_end_indices):
                end = min(end, num_blocks)
                for idx in range(start, end):
                    first_row = 0 if self.attention == "bidirectional" else idx
                    layout[h, first_row:, idx] = 1
                    if self.horizontal_global_attention:
                        layout[h, idx, :] = 1
        if self.attention == "unidirectional":
            layout[h] = np.tril(layout[h])
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_random_layout(h, layout)
            layout = self.set_local_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird (Zaheer et al. 2020): random + sliding window + global
    first/last blocks (reference sparsity_config.py:421)."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_random_blocks=1,
                 num_sliding_window_blocks=3,
                 num_global_blocks=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks

    def set_random_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_random_blocks:
            raise ValueError(
                f"Number of random blocks ({self.num_random_blocks}) must be smaller "
                f"than overall number of blocks in a row ({num_blocks})")
        for row in range(num_blocks):
            rnd_cols = random.sample(range(num_blocks), self.num_random_blocks)
            layout[h, row, rnd_cols] = 1
        return layout

    def set_sliding_window_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_sliding_window_blocks:
            raise ValueError(
                f"Number of sliding window blocks ({self.num_sliding_window_blocks}) "
                f"must be smaller than overall number of blocks in a row ({num_blocks})")
        w = self.num_sliding_window_blocks // 2
        for row in range(num_blocks):
            start = max(0, row - w)
            end = min(row + w + 1, num_blocks)
            layout[h, row, start:end] = 1
        return layout

    def set_global_layout_itc(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_global_blocks:
            raise ValueError(
                f"Number of global blocks ({self.num_global_blocks}) must be smaller "
                f"than overall number of blocks in a row ({num_blocks})")
        layout[h, 0:self.num_global_blocks, :] = 1
        layout[h, :, 0:self.num_global_blocks] = 1
        layout[h, -self.num_global_blocks:, :] = 1
        layout[h, :, -self.num_global_blocks:] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_random_layout(h, layout)
            layout = self.set_sliding_window_layout(h, layout)
            layout = self.set_global_layout_itc(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + explicit global block
    indices/ranges (reference sparsity_config.py:544)."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_sliding_window_blocks=3,
                 global_block_indices=(0,),
                 global_block_end_indices=None):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = list(global_block_indices)
        if global_block_end_indices is not None:
            if len(global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    "global_block_indices and global_block_end_indices must have "
                    "the same length")
            for start, end in zip(global_block_indices, global_block_end_indices):
                if start >= end:
                    raise ValueError(
                        f"global block start index ({start}) must be smaller than "
                        f"its end index ({end})")
        self.global_block_end_indices = (list(global_block_end_indices)
                                         if global_block_end_indices is not None else None)

    def set_sliding_window_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_sliding_window_blocks:
            raise ValueError(
                f"Number of sliding window blocks ({self.num_sliding_window_blocks}) "
                f"must be smaller than overall number of blocks in a row ({num_blocks})")
        w = self.num_sliding_window_blocks // 2
        for row in range(num_blocks):
            start = max(0, row - w)
            end = min(row + w + 1, num_blocks)
            layout[h, row, start:end] = 1
        return layout

    def set_global_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if self.global_block_end_indices is None:
            for idx in self.global_block_indices:
                if idx < num_blocks:
                    layout[h, :, idx] = 1
                    layout[h, idx, :] = 1
        else:
            for start, end in zip(self.global_block_indices, self.global_block_end_indices):
                end = min(end, num_blocks)
                layout[h, :, start:end] = 1
                layout[h, start:end, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_sliding_window_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


def config_to_sparsity(sa_config, num_heads):
    """Build a SparsityConfig from the json section
    (deepspeed_tpu/config/config.py SparseAttentionConfig) — the dispatch
    the reference does in config.py:236-406."""
    mode = sa_config.mode
    if mode == "dense":
        return DenseSparsityConfig(num_heads, sa_config.block,
                                   sa_config.different_layout_per_head)
    if mode == "fixed":
        return FixedSparsityConfig(
            num_heads, sa_config.block, sa_config.different_layout_per_head,
            sa_config.num_local_blocks, sa_config.num_global_blocks,
            sa_config.attention, sa_config.horizontal_global_attention,
            sa_config.num_different_global_patterns)
    if mode == "variable":
        return VariableSparsityConfig(
            num_heads, sa_config.block, sa_config.different_layout_per_head,
            sa_config.num_random_blocks, sa_config.local_window_blocks,
            sa_config.global_block_indices, sa_config.global_block_end_indices,
            sa_config.attention, sa_config.horizontal_global_attention)
    if mode == "bigbird":
        return BigBirdSparsityConfig(
            num_heads, sa_config.block, sa_config.different_layout_per_head,
            sa_config.num_random_blocks, sa_config.num_sliding_window_blocks,
            sa_config.num_global_blocks)
    if mode == "bslongformer":
        return BSLongformerSparsityConfig(
            num_heads, sa_config.block, sa_config.different_layout_per_head,
            sa_config.num_sliding_window_blocks, sa_config.global_block_indices,
            sa_config.global_block_end_indices)
    raise NotImplementedError(f"Given sparsity mode, {mode}, has not been implemented yet!")
