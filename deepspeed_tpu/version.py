"""Version info (reference: version.txt + setup.py git-hash embedding)."""

import subprocess

__version__ = "0.1.0"


def _git(cmd):
    try:
        return subprocess.check_output(["git"] + cmd,
                                       stderr=subprocess.DEVNULL).decode().strip()
    except Exception:
        return "unknown"


def git_hash():
    return _git(["rev-parse", "--short", "HEAD"])


def git_branch():
    return _git(["rev-parse", "--abbrev-ref", "HEAD"])
