"""Benchmark — GPT-2 training MFU on the local TPU chip.

Prints up to TWO JSON lines — an insurance line with every number except
the long-running gpt2_xl case, then the authoritative final line including
it. THE LAST COMPLETE JSON LINE IS THE RESULT (the driver tails output).
North star (BASELINE.json): GPT-2 ZeRO-3 at ≥45% MFU → vs_baseline = MFU/45.

Model flops per step use the standard 6·N·T (+ attention) accounting; peak
chip flops resolved from the device kind.

Sections + budgets (r5: the run hit the driver's wall clock, rc=124, and
the JSON tail was truncated mid-object): every optional section is gated
by a SectionRunner that (a) honours ``--sections a,b,c`` to run a subset,
(b) skips anything whose estimated cost no longer fits ``--budget``
seconds of global wall clock, and (c) converts section exceptions into
``{"skipped": reason}`` entries — so EVERY run prints complete, parseable
JSON lines and records what it skipped in
``detail.sections_skipped``. ``--list-sections`` prints the names.
"""

import argparse
import json
import os
import sys
import time

import numpy as np


class SectionRunner:
    """Gate + error-fence for bench sections. ``selected`` empty → all
    sections run (budget permitting); skips are recorded with reasons."""

    def __init__(self, selected=(), budget_s=0.0):
        self.t0 = time.time()
        self.selected = tuple(s for s in selected if s)
        self.budget = float(budget_s or 0.0)
        self.skipped = {}

    def elapsed(self):
        return time.time() - self.t0

    def remaining(self):
        return max(0.0, self.budget - self.elapsed()) if self.budget \
            else float("inf")

    def want(self, name, est_s=60.0):
        if self.selected and name not in self.selected:
            self.skipped[name] = "deselected (--sections)"
            return False
        if self.budget and est_s > self.remaining():
            self.skipped[name] = (
                f"budget: {self.elapsed():.0f}s elapsed of "
                f"{self.budget:.0f}s, section estimate {est_s:.0f}s")
            return False
        return True

    def run(self, name, fn, est_s=60.0):
        """Run ``fn`` if selected + affordable; any outcome is a JSON-able
        value ({"skipped": reason} when gated or thrown)."""
        if not self.want(name, est_s):
            return {"skipped": self.skipped[name]}
        try:
            return fn()
        except Exception as e:              # noqa: BLE001 — fence, record
            self.skipped[name] = f"error: {str(e)[:200]}"
            return {"skipped": self.skipped[name]}


BENCH_SECTIONS = ("bert", "train", "sparse", "decode", "llama7b", "moe",
                  "zero3_prefetch", "zero3_hier", "onebit_comm", "aio",
                  "nvme_param", "nvme_xl",
                  "elastic_ckpt", "fault_recovery", "serving",
                  "serving_prefix", "serving_spec", "serving_elastic",
                  "serving_disagg", "infinity6b", "xl")


# ---------------------------------------------------------------------------
# --compare: the CI regression gate (ISSUE 6). Diffs the headline
# metrics of two bench result documents and exits nonzero when any
# common metric regressed past the threshold. Handles both the
# bench-native result JSON and the driver-captured BENCH_rXX.json
# format ({"parsed": {metric, value, ...}}). This path never imports
# jax — it runs on artifact files anywhere.
# ---------------------------------------------------------------------------

def _load_doc(path):
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError) as e:
        raise SystemExit(f"--compare: cannot load {path}: {e}")


def provenance(jax_version=None):
    """Stamp for every result JSON (``meta.provenance``, ISSUE 12
    satellite): the ±25% box swing between identical-content runs keeps
    getting rediscovered by hand — a compare that shows two different
    hostnames/cpu_counts (or the same sha measured twice) answers "is
    this a regression or a different box" without archaeology. Pass
    ``jax_version`` from the caller that already imported jax; this
    function itself must stay importable jax-free (the --candidate
    compare path)."""
    import platform
    import socket
    import subprocess
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        sha = subprocess.check_output(
            ["git", "rev-parse", "--short", "HEAD"], cwd=here,
            stderr=subprocess.DEVNULL).decode().strip()
    except Exception:
        sha = "unknown"
    return {
        "git_sha": sha,
        "hostname": socket.gethostname(),
        "cpu_count": os.cpu_count(),
        "jax_version": jax_version or "unknown",
        "python_version": platform.python_version(),
    }


def _doc_provenance(doc):
    """meta.provenance of a result document (driver-captured docs may
    carry it beside ``parsed``), or None."""
    for d in (doc, doc.get("parsed") if isinstance(doc.get("parsed"),
                                                   dict) else {}):
        if isinstance(d, dict):
            p = (d.get("meta") or {}).get("provenance")
            if isinstance(p, dict):
                return p
    return None


def _num(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def headline_metrics(doc):
    """Flatten a bench result document into ``{name: (value,
    direction)}`` where direction is +1 for higher-is-better and -1
    for lower-is-better. Sections that were skipped (or absent)
    contribute nothing — the gate compares only metrics BOTH runs
    measured."""
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and _num(parsed.get("value")):
        # driver-captured format: the parsed line IS a bench-native doc
        # (r01-r03 carry the full detail; r05 only the headline) —
        # recurse so whatever survived the tail capture gates
        return headline_metrics(parsed)
    out = {}
    if _num(doc.get("value")):
        out[doc.get("metric", "headline")] = (doc["value"], +1)
    d = doc.get("detail") or {}

    def grab(name, container, key, direction):
        v = container.get(key) if isinstance(container, dict) else None
        if _num(v):
            out[name] = (v, direction)

    grab("tokens_per_sec", d, "tokens_per_sec", +1)
    grab("samples_per_sec_per_chip", d, "samples_per_sec_per_chip", +1)
    grab("step_time_ms", d, "step_time_ms", -1)
    grab("bert_base_seq128_samples_per_sec", d,
         "bert_base_seq128_samples_per_sec", +1)
    dec = d.get("decode")
    if isinstance(dec, dict):
        for name, entry in sorted(dec.items()):
            if not isinstance(entry, dict):
                continue
            if name == "serving_continuous_batching":
                grab("serving.requests_per_sec", entry,
                     "requests_per_sec_continuous", +1)
                grab("serving.decode_tokens_per_sec", entry,
                     "decode_tokens_per_sec_continuous", +1)
                grab("serving.ttft_p99_s", entry, "ttft_p99_s", -1)
            elif name == "serving_hot_prefix":
                # ISSUE 9: repeat-prefix admissions must keep aliasing
                # resident pages (a drop means the prefix index broke)
                grab("serving.prefix_hit_rate", entry,
                     "prefix_hit_rate", +1)
            elif name == "serving_spec_decode":
                # ISSUE 9: batched verification must keep beating the
                # one-model-call-per-token decode loop at b1
                grab("serving.spec_decode_speedup", entry,
                     "spec_decode_speedup", +1)
            elif name == "serving_disagg":
                # ISSUE 14: the role split must keep beating colocated
                # head-of-line TTFT on the deterministic mixed trace
                grab("serving.disagg_ttft_p99", entry,
                     "ttft_p99_s_disagg", -1)
                # ISSUE 17: the 2-real-process transport leg's TTFT
                # tail (wire codec + collective hop in the handoff
                # path) — gate against BENCH_r16.json or newer
                grab("serving.disagg_xproc_ttft_p99", entry,
                     "ttft_p99_s_disagg_xproc", -1)
                # ISSUE 18: multi-decode scale-out — world-3 aggregate
                # decode tok/s over world-2's single decode rank must
                # keep >= 1.6x (LPT balancing holding both ranks near
                # single-rank occupancy); gate vs BENCH_r18 or newer
                grab("serving.decode_scaleout_tok_s_ratio", entry,
                     "decode_scaleout_tok_s_ratio", +1)
            elif name == "serving_elastic":
                # ISSUE 11: one replica kill + one graceful drain must
                # keep recovering EVERY request (greedy replay makes
                # recovery token-lossless, so 1.0 is the only pass);
                # token-loss/restore-latency ride the detail unguarded
                # (latency is box-noise-bound on the CPU harness)
                grab("serving.elastic_recovered_fraction", entry,
                     "recovered_fraction", +1)
            else:
                grab(f"decode.{name}.decode_tokens_per_sec", entry,
                     "decode_tokens_per_sec", +1)
    grab("moe.tokens_per_sec", d.get("moe"), "tokens_per_sec", +1)
    # ISSUE 8: the tile-granular fused_matmul gather must not regress
    # vs ring-mode prefetch (CPU-proxy step-time ratio, higher=better)
    grab("zero3_prefetch.fused_vs_ring", d.get("zero3_prefetch"),
         "fused_vs_ring", +1)
    # ISSUE 10: the hierarchical exchange must keep the slow-hop
    # bytes-on-wire reduction (static cost-model ratio, >= 4x; a drop
    # means the per-bucket policy stopped compressing the slow axis)
    grab("onebit_comm.bytes_reduction", d.get("onebit_comm"),
         "bytes_reduction", +1)
    # ISSUE 16: the link-aware ZeRO-3 prefetch stream must keep its
    # modeled slow-hop reduction vs the FLAT single-ring baseline
    # (static cost-model ratio, >= 2x at 2x4; a drop means a gather or
    # grad leg fell off the two-level schedule or stopped compressing)
    grab("zero3_hier.inter_bytes_reduction", d.get("zero3_hier"),
         "inter_bytes_reduction", +1)
    grab("nvme_param.steady_step_s", d.get("nvme_param_tier"),
         "steady_step_s", -1)
    # ISSUE 20: the honest NVMe path. max_params_b is the single-chip
    # scale proof under O_DIRECT streaming (must stay >= 10B once
    # BENCH_r19 records it); the o_direct stall share is the
    # page-cache-free swap cost the step actually pays — gate both
    # against BENCH_r19.json or newer
    grab("nvme_xl.max_params_b", d.get("nvme_xl"), "max_params_b", +1)
    nv = d.get("nvme_param_tier")
    grab("nvme_param.o_direct_stall_share",
         nv.get("o_direct") if isinstance(nv, dict) else None,
         "stall_share_of_step", -1)
    grab("infinity.steady_step_s", d.get("infinity_6b"),
         "steady_step_s", -1)
    # elastic snapshots (ISSUE 7) stay OUT of the gated set on purpose:
    # step_s_async/blocking_save_s are ~0.2-0.4 s page-cache timings
    # with documented ±20% box noise — gating them at 5% makes CI
    # flaky with no real regression (the numbers live in the section
    # detail; the stable signals are ckpt_stall_s == 0 and
    # overhead_pct_at_interval_100 < 1)
    return out


def compare_docs(prior, candidate, threshold=0.05):
    """Structured diff of two result documents; ``regressions`` lists
    common metrics whose direction-signed change is worse than
    ``threshold`` (a fraction, e.g. 0.05 = 5%)."""
    pm, cm = headline_metrics(prior), headline_metrics(candidate)
    compared, regressions, improvements = {}, [], []
    for k in sorted(set(pm) & set(cm)):
        pv, direction = pm[k]
        cv, _ = cm[k]
        if pv == 0:
            continue
        delta = (cv / pv - 1.0) * direction    # > 0 means better
        compared[k] = {
            "prior": pv, "candidate": cv,
            "delta_pct": round(delta * 100, 2),
            "better": "higher" if direction > 0 else "lower",
        }
        if delta < -threshold:
            regressions.append(k)
        elif delta > threshold:
            improvements.append(k)
    return {
        "threshold_pct": round(threshold * 100, 2),
        "compared": compared,
        "regressions": regressions,
        "improvements": improvements,
        "only_in_prior": sorted(set(pm) - set(cm)),
        "only_in_candidate": sorted(set(cm) - set(pm)),
    }


def compare_and_report(prior_doc, candidate_doc, threshold):
    """Print the per-metric diff + a machine-readable summary line;
    return the process exit code (0 pass, 3 regression)."""
    rep = compare_docs(prior_doc, candidate_doc, threshold)
    # both sides' provenance up front: a "regression" measured on a
    # different hostname/cpu_count is the box, not the code
    for side, doc in (("prior", prior_doc), ("candidate", candidate_doc)):
        prov = _doc_provenance(doc)
        print(f"  {side} provenance: "
              + (json.dumps(prov, sort_keys=True) if prov
                 else "<none recorded>"))
    for k, row in rep["compared"].items():
        flag = "REGRESSION" if k in rep["regressions"] else (
            "improved" if k in rep["improvements"] else "ok")
        print(f"  {k}: {row['prior']} -> {row['candidate']} "
              f"({row['delta_pct']:+.2f}%, {row['better']}-is-better) "
              f"[{flag}]")
    print(json.dumps({"compare": rep}), flush=True)
    if not rep["compared"]:
        print("WARN: no common headline metrics to compare "
              "(gate passes vacuously)")
        return 0
    if rep["regressions"]:
        print(f"FAIL: {len(rep['regressions'])} metric(s) regressed "
              f"past {rep['threshold_pct']}%: "
              f"{', '.join(rep['regressions'])}")
        return 3
    print(f"PASS: no headline metric regressed past "
          f"{rep['threshold_pct']}% "
          f"({len(rep['compared'])} compared)")
    return 0


def _enable_compile_cache():
    """Persistent XLA compilation cache (repo-local): the 1.5B offload
    program compiles in ~40 min through the tunneled backend; caching it
    makes the gpt2_xl bench case a cache-hit re-run on later invocations
    on the same machine."""
    import jax
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".jax_cache")
    os.makedirs(path, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 10)
    except Exception:
        pass


def peak_flops(device):
    """Single source of truth: profiling/flops_profiler.py (the engine's
    telemetry MFU gauge resolves the same table)."""
    from deepspeed_tpu.profiling.flops_profiler import peak_device_flops
    return peak_device_flops(device)


def model_flops_per_token(cfg):
    """6N + attention term (12·L·S·E per token) — canonical copy in
    profiling/flops_profiler.py, shared with the MFU tests."""
    from deepspeed_tpu.profiling import flops_profiler
    return flops_profiler.model_flops_per_token(cfg)


XL_WARM_SENTINEL = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".jax_cache", "xl_warmed")


def bench_xl_case(budget_s=2400):
    """gpt2_xl 1.5B ZeRO-Offload in a bounded subprocess (VERDICT r2 item
    6: driver-visible, produced by bench.py itself).

    The 48-layer offload program costs ~17-20 min of REMOTE compile that
    the client-side persistent cache cannot capture, plus two ~6-min
    host-bound steps, so the case only runs once bench_xl.py has
    completed on this machine (it drops a sentinel proving the
    configuration finishes); a machine without the sentinel reports
    skipped with instructions instead of burning the budget blind.

    The tunneled chip claim is shared, not exclusive (verified: a second
    process initializes the backend while another holds it), so this can
    run after the parent's measurements; the parent clears its caches
    first so the subprocess gets the HBM."""
    import subprocess
    here = os.path.dirname(os.path.abspath(__file__))
    if not os.path.exists(XL_WARM_SENTINEL):
        return {"skipped": "compilation cache cold for the 1.5B program "
                           "(~40 min compile through the tunnel); run "
                           "`python bench_xl.py` once to warm it — later "
                           "bench.py runs then include this case"}
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(here, "bench_xl.py"),
             "--steps", "1"],
            capture_output=True, text=True, timeout=budget_s, cwd=here)
    except subprocess.TimeoutExpired:
        return {"skipped": f"budget {budget_s}s exceeded (remote compile "
                           f"is uncacheable ~20 min + 2 host-bound steps; "
                           f"chip/HBM contention with the parent process "
                           f"can also stretch this)"}
    if proc.returncode == 0:
        for line in reversed((proc.stdout or "").strip().splitlines()):
            try:
                parsed = json.loads(line)
            except (ValueError, json.JSONDecodeError):
                continue
            if isinstance(parsed, dict):
                return parsed
    return {"skipped": f"rc={proc.returncode}: "
                       f"{(proc.stderr or '')[-300:]}"}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sections", default="",
                    help="comma-separated subset of sections to run "
                         f"(default all): {','.join(BENCH_SECTIONS)}")
    ap.add_argument("--budget", type=float, default=None,
                    help="global wall-clock budget in seconds; sections "
                         "whose estimate no longer fits are skipped and "
                         "recorded (0 = unlimited; default: "
                         "$DSTPU_BENCH_BUDGET or 3000 — r5 ran unbounded, "
                         "hit the driver's wall clock at rc=124, and lost "
                         "the trailing sections to a SIGKILL instead of "
                         "an explicit skip)")
    ap.add_argument("--list-sections", action="store_true")
    ap.add_argument("--compare", metavar="PRIOR.json", default="",
                    help="regression gate: diff this run's headline "
                         "metrics against a prior result document "
                         "(bench-native JSON or a driver BENCH_rXX.json)"
                         " and exit nonzero past the threshold; with "
                         "--candidate, diff two files WITHOUT running "
                         "the bench (no jax import — CI-usable on "
                         "artifacts)")
    ap.add_argument("--candidate", metavar="CURRENT.json", default="",
                    help="candidate result file for --compare "
                         "(skips the bench run)")
    ap.add_argument("--regression-threshold", type=float, default=0.05,
                    help="fractional worsening that fails the gate "
                         "(default 0.05 = 5%%)")
    args = ap.parse_args(argv)
    if args.list_sections:
        print(json.dumps(list(BENCH_SECTIONS)))
        return 0
    if args.candidate and not args.compare:
        raise SystemExit("--candidate requires --compare PRIOR.json")
    if args.compare and args.candidate:
        # pure-file gate: no bench run, no jax import
        return compare_and_report(_load_doc(args.compare),
                                  _load_doc(args.candidate),
                                  args.regression_threshold)
    selected = [s.strip() for s in args.sections.split(",") if s.strip()]
    unknown = [s for s in selected if s not in BENCH_SECTIONS]
    if unknown:
        raise SystemExit(f"unknown sections {unknown}; "
                         f"choose from {list(BENCH_SECTIONS)}")
    if args.budget is None:
        # the default run gets a budget UNDER the driver's wall clock so
        # trailing sections record an explicit skip instead of the whole
        # process dying rc=124 mid-JSON (BENCH_r05)
        args.budget = float(os.environ.get("DSTPU_BENCH_BUDGET", 3000))
    runner = SectionRunner(selected, args.budget)

    import jax
    _enable_compile_cache()
    import jax.numpy as jnp
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

    # chip claim can lag a just-exited subprocess (exclusive + flaky)
    for attempt in range(6):
        try:
            jax.devices()
            break
        except Exception:
            if attempt == 5:
                raise
            time.sleep(20)
    if all(d.platform == "cpu" for d in jax.devices()) \
            and not os.environ.get("DSTPU_BENCH_ALLOW_CPU"):
        # a failed accelerator init silently falls back to CPU; an MFU
        # against TPU peak computed from a CPU run would be absurd
        raise RuntimeError(
            "bench.py found only CPU devices; the TPU claim failed "
            "(set DSTPU_BENCH_ALLOW_CPU=1 to run on CPU anyway)")

    dev = jax.devices()[0]

    # BERT headline first: its state must be freed before the 774M model
    # claims most of HBM
    bert_sps = runner.run(
        "bert", lambda: bench_bert(dstpu, make_mesh, MeshConfig, dev),
        est_s=180)
    jax.clear_caches()

    train = runner.run(
        "train", lambda: bench_train_gpt2(dstpu, make_mesh, MeshConfig,
                                          dev, jnp),
        est_s=600)
    jax.clear_caches()
    sparse = runner.run("sparse", lambda: bench_sparse_attention(jnp),
                        est_s=180)
    jax.clear_caches()
    decode = runner.run("decode", lambda: bench_decode(jnp), est_s=900)
    jax.clear_caches()
    if not isinstance(decode, dict):
        decode = {"skipped": str(decode)}
    # llama7b + serving ride the decode section of the JSON but are
    # gated INDEPENDENTLY through the runner, so selecting/skipping
    # either always records a reason even when decode itself skipped
    for bs in (1, 8):
        decode[f"llama7b_b{bs}_int8"] = runner.run(
            "llama7b", lambda bs=bs: bench_llama_decode(jnp, bs=bs),
            est_s=600)
        jax.clear_caches()
    decode["serving_continuous_batching"] = runner.run(
        "serving", bench_serving, est_s=600)
    jax.clear_caches()
    # ISSUE 9: prefix-sharing + speculative decoding ride the serving
    # section (same CPU-proxy model sizing) but gate independently
    decode["serving_hot_prefix"] = runner.run(
        "serving_prefix", bench_serving_hot_prefix, est_s=300)
    jax.clear_caches()
    decode["serving_spec_decode"] = runner.run(
        "serving_spec", bench_serving_spec_decode, est_s=300)
    jax.clear_caches()
    # ISSUE 11: elastic serving — replica kill + graceful drain
    # recovery and watchdog-driven autoscale under burst overload
    decode["serving_elastic"] = runner.run(
        "serving_elastic", bench_serving_elastic, est_s=420)
    jax.clear_caches()
    # ISSUE 14: disaggregated prefill/decode + SLO router vs the
    # colocated engine on the identical deterministic mixed trace
    decode["serving_disagg"] = runner.run(
        "serving_disagg", bench_serving_disagg, est_s=420)
    jax.clear_caches()
    moe = runner.run(
        "moe", lambda: bench_moe(dstpu, make_mesh, MeshConfig, dev),
        est_s=180)
    zero3_prefetch = runner.run("zero3_prefetch", bench_zero3_prefetch,
                                est_s=300)
    jax.clear_caches()
    zero3_hier = runner.run("zero3_hier", bench_zero3_hier, est_s=300)
    jax.clear_caches()
    onebit_comm = runner.run("onebit_comm", bench_onebit_comm, est_s=240)
    jax.clear_caches()

    # NVMe/disk tier throughput (reference's aio perf harness role,
    # csrc/aio/py_test): 128 MB write+read through the async-IO library,
    # median of 3 passes + cold first read (pinned methodology — see
    # quick_throughput) — sizes the ZeRO-Infinity swap tier
    def _aio():
        from tests.perf.aio_bench import quick_throughput
        return quick_throughput(mb=128)
    aio = runner.run("aio", _aio, est_s=120)
    nvme_param = runner.run(
        "nvme_param",
        lambda: bench_nvme_param_tier(dstpu, make_mesh, MeshConfig, dev),
        est_s=300)
    jax.clear_caches()
    # ISSUE 20: the O_DIRECT streaming scale proof — 10B+ params on one
    # chip with bounded host residency, measured against the page-cache-
    # free device numbers (plus a small-scale loss-parity leg)
    nvme_xl = runner.run(
        "nvme_xl",
        lambda: bench_nvme_xl(dstpu, make_mesh, MeshConfig, dev),
        est_s=600)
    jax.clear_caches()
    elastic_ckpt = runner.run(
        "elastic_ckpt",
        lambda: bench_elastic_ckpt(dstpu, make_mesh, MeshConfig, dev),
        est_s=240)
    jax.clear_caches()   # free HBM before the 1.5B subprocess needs it
    # ISSUE 15: supervisor MTTR — detect latency + restart-to-first-step
    # over real (stdlib) child processes; seconds, not minutes
    fault_recovery = runner.run("fault_recovery", bench_fault_recovery,
                                est_s=30)

    tdet = train if isinstance(train, dict) else {}
    skipped_train = "skipped" in tdet
    result = {
        "meta": {"provenance": provenance(jax_version=jax.__version__)},
        "metric": "gpt2_large_774m_zero3_mfu",
        "value": None if skipped_train else tdet["mfu_pct"],
        "unit": "%MFU",
        "vs_baseline": None if skipped_train
        else round(tdet["mfu_pct"] / 45.0, 3),
        "detail": {
            **({"train_skipped": tdet.get("skipped")} if skipped_train
               else {k: v for k, v in tdet.items() if k != "mfu_pct"}),
            # fused-kernel BERT pretraining headline (reference: 272
            # samples/s @ seq128 on one V100, 2020-05-28 blog)
            "bert_base_seq128_samples_per_sec": bert_sps,
            # serving decode throughput (reference ships 6.5k LoC of
            # inference kernels because decode perf mattered; here the
            # fused inference layer + KV cache, models/gpt2_inference.py)
            "decode": decode,
            # block-sparse vs dense flash attention fwd+bwd (reference
            # claim: up to 6.1x + 10x longer sequences; 16k runs the
            # streaming kernel past the old S*D cap)
            "sparse_attention": sparse,
            # 1.5B ZeRO-Offload on this one chip (bounded subprocess; the
            # honest MFU measures the harness's 1-core host, not the
            # architecture — see bench_xl.py). Filled by the later print;
            # this placeholder survives if the run is cut short.
            "gpt2_xl": {"skipped": "run interrupted before the XL case"},
            # async-IO tier (io_uring or thread pool; cache-cold read)
            "aio_disk": aio,
            # ZeRO-Infinity parameter tier: params REST on NVMe between
            # steps (swap files + parked device arrays), streaming disk ->
            # staging -> HBM around each step. On this harness the h2d leg
            # crosses the ~35 MB/s tunnel, so the step time measures the
            # tunnel; on a TPU-VM the same path is PCIe-fed.
            "nvme_param_tier": nvme_param,
            # O_DIRECT streaming scale proof (ISSUE 20): a 10B+ tiled
            # parameter set parks on disk and streams back through the
            # bounded staging window twice — first pass vs steady pass
            # at device bandwidth (no page-cache assist), host RSS
            # bounded by the window, small-scale loss parity vs the
            # in-memory engine
            "nvme_xl": nvme_xl,
            # elastic async snapshots (ISSUE 7): step-time overhead of
            # checkpointing every few steps through the write-behind aio
            # handle vs the blocking save stall it replaces
            "elastic_ckpt": elastic_ckpt,
            # fault-tolerant training supervisor (ISSUE 15): rank-death
            # detect latency + restart-to-first-step MTTR over real
            # child processes (stdlib workers — the machinery's cost,
            # not an engine compile)
            "fault_recovery": fault_recovery,
            # expert-parallel MoE training throughput (beyond-reference
            # component; routing einsums regress invisibly without it)
            "moe": moe,
            # ZeRO-3 layer-wise gather prefetch on vs off (ISSUE 3) and
            # ring vs tile-granular fused_matmul gather (ISSUE 8, with
            # the gather-wait/compute exposure breakdown): on a
            # single-chip harness this is the 8-virtual-device CPU
            # step-time proxy (see bench_zero3_prefetch); on a slice it
            # measures the real ICI overlap behind the headline MFU
            "zero3_prefetch": zero3_prefetch,
            # link-aware ZeRO-3 prefetch stream (ISSUE 16): modeled
            # slow-hop byte reduction of the two-level compressed
            # schedule vs the flat single-ring baseline + step times;
            # 8-virtual-device synthetic-split proxy (the REAL
            # process-boundary path is pinned by
            # tests/test_multiprocess_dist.py)
            "zero3_hier": zero3_hier,
            # hierarchical link-aware 1-bit gradient exchange (ISSUE
            # 10): slow-hop bytes-on-wire reduction + step times; on a
            # single-host harness the 8-virtual-device synthetic-split
            # proxy (the REAL process-boundary path is pinned by
            # tests/test_multiprocess_dist.py)
            "onebit_comm": onebit_comm,
            "sections_skipped": runner.skipped,
        },
    }

    def short(r):
        # the driver records a bounded TAIL of stdout; the full result
        # line outgrew it in r4 and the headline number vanished. ALWAYS
        # end with a short headline-only line so the tail is
        # self-sufficient regardless of how much detail precedes it.
        return json.dumps({k: r[k] for k in
                           ("metric", "value", "unit", "vs_baseline")})

    # insurance line: the 6B + XL cases below can take many minutes; if
    # the harness kills us mid-way, the LAST complete JSON line still
    # carries every other number. Later (authoritative) lines replace it.
    result["detail"]["sections_skipped"] = dict(runner.skipped)
    print(json.dumps(result), flush=True)
    print(short(result), flush=True)

    # the max-params-per-chip scale proof (ZeRO-Infinity, ≥6B on 16 GB)
    # — free every earlier section's device state first; the 6B case
    # needs nearly the whole chip
    jax.clear_caches()
    inf6b = runner.run("infinity6b",
                       lambda: bench_infinity_6b(dstpu, dev), est_s=1200)
    result["detail"]["infinity_6b"] = inf6b
    result["detail"]["max_params_per_chip_b"] = \
        inf6b.get("params_b", 1.558)   # gpt2_xl's 1.558B is the floor
    result["detail"]["sections_skipped"] = dict(runner.skipped)
    print(json.dumps(result), flush=True)
    print(short(result), flush=True)

    if runner.want("xl", est_s=600):
        xl_budget = min(2400.0, runner.remaining())
        result["detail"]["gpt2_xl"] = bench_xl_case(
            budget_s=xl_budget if runner.budget else 2400)
    else:
        result["detail"]["gpt2_xl"] = {"skipped": runner.skipped["xl"]}
    result["detail"]["sections_skipped"] = dict(runner.skipped)
    print(json.dumps(result))
    print(short(result))

    if args.compare:
        # the gate rides a full run: this run's result is the candidate
        return compare_and_report(_load_doc(args.compare), result,
                                  args.regression_threshold)
    return 0


def bench_train_gpt2(dstpu, make_mesh, MeshConfig, dev, jnp):
    """The headline section: GPT-2 large (774M) ZeRO-3 training MFU.
    Returns a dict whose ``mfu_pct`` is the bench metric; everything
    else lands in the result detail."""
    import jax
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    mesh = make_mesh(MeshConfig(data=1), devices=[dev])

    seq = 1024
    # GPT-2 large (774M), the largest dense config that trains in 16 GB.
    # Measured fastest recipe on v5e (see docs/perf_tuning.md): bs8
    # (8192-row matmuls feed the MXU at its efficiency knee), remat with
    # the dots_flash_fc_lean policy (keep mlp matmuls + flash residuals;
    # qkv and the attention projection recompute), fused chunked
    # head+loss (no [B,S,V] buffer), bf16 gradients + a bf16 Adam first
    # moment (fp32 update math; the second moment stays fp32 — a bf16
    # EMA freezes below its ulp).
    model_cfg = GPT2Config(vocab_size=50304, n_positions=seq, n_embd=1280,
                           n_layer=36, n_head=20, dtype=jnp.bfloat16,
                           scan_layers=True, remat=True,
                           remat_policy="dots_flash_fc_lean", loss_chunk=1024)
    batch_size = 8

    cfg = {
        "train_batch_size": batch_size,
        "gradient_accumulation_steps": 1,
        "zero_optimization": {"stage": 3},
        "bf16": {"enabled": True},
        "data_types": {"grad_dtype": "bf16"},
        "gradient_clipping": 1.0,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 1e-4, "weight_decay": 0.01,
                                 "moment_dtype": "bf16"}},
        "steps_per_print": 1000,
    }
    model = GPT2LMHeadModel(model_cfg)
    # telemetry: the engine records into the process-wide registry; a
    # fresh window here keeps earlier sections' train/* values out of
    # this section's snapshot
    from deepspeed_tpu.telemetry import default_registry
    default_registry().reset()
    engine, _, _, _ = dstpu.initialize(config=cfg, model=model, mesh=mesh)

    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, 50304, size=(batch_size, seq))
             .astype(np.int32)}

    # warmup (compile); force with a DATA-dependent readback — on tunneled
    # backends block_until_ready can return before execution finishes, so
    # only a device_get of a value produced by the step is a trustworthy
    # fence
    for _ in range(2):
        loss = engine.train_batch(batch)
    float(jax.device_get(loss))
    engine.telemetry_flush()   # open a steady-state telemetry window

    # three timed windows, best wins: the tunneled chip shows ±5%
    # run-to-run noise and the benchmark should report the machine, not
    # the tunnel. 30 iters/window because the window's ONE readback fence
    # costs a full tunnel round trip (~100 ms measured — r4 finding): at
    # 12 iters that fence inflated every step by ~8 ms (~0.8 MFU points).
    iters = 30
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = engine.train_batch(batch)
        float(jax.device_get(loss))
        best = min(best, (time.perf_counter() - t0) / iters)
        # fold each timed window into the step-time histogram (the
        # fence above already paid the sync). The batch lets the first
        # fold price MFU from the compiled step's cost analysis —
        # between windows, outside every timed region.
        engine.telemetry_flush(batch)
    # the residual fence share still inside the window, measured on
    # scalars this process has NOT read yet (a re-read of `loss` would
    # hit the client-side npy cache and measure ~0 instead of the
    # tunnel RTT). MINIMUM of three samples: the fence is a pure-RTT
    # floor, and a single sample can absorb a host descheduling blip —
    # one polluted 2.4 s sample inflated an r5 reading by +10 MFU
    # points before this guard.
    fences = []
    for probe in (engine.state.global_step, engine.state.skipped_steps,
                  engine.state.global_step + 0):
        t0 = time.perf_counter()
        int(jax.device_get(probe))
        fences.append(time.perf_counter() - t0)
    fence_s = min(fences)
    dt = best - fence_s / iters

    tokens_per_step = batch_size * seq
    flops_per_step = model_flops_per_token(model_cfg) * tokens_per_step
    achieved = flops_per_step / dt
    mfu = achieved / peak_flops(dev)
    samples_per_sec = batch_size / dt
    final_loss = float(jax.device_get(loss))
    # exact compiled-buffer memory breakdown (free: executable cache hit)
    mem = engine.train_step_memory_stats(batch)
    params_b = round(model_cfg.num_params() / 1e9, 3)

    # per-phase wall-clock breakdown (reference wall_clock_breakdown,
    # engine.py:1028-1047): the instrumented mode splits the fused program
    # into fwd / fwd+bwd / apply with data-dependent fences, so phase times
    # are real measurements — fwd+bwd don't sum to the fused step time
    # (which keeps cross-phase fusion and no fences)
    engine._config.wall_clock_breakdown = True
    engine.train_batch(batch)          # compiles the loss + apply programs
    engine.wall_clock_times(reset=True)
    for _ in range(3):
        engine.train_batch(batch)
    phase_ms = {k: round(v / 3 * 1000, 1)
                for k, v in engine.wall_clock_times().items()}
    engine._config.wall_clock_breakdown = False

    # unified-telemetry snapshot for the BENCH record: step-time
    # percentiles over the timed windows, per-phase span histograms
    # (fed by the instrumented runs above), and the engine's own MFU
    # gauge (flops from the compiled step's cost analysis, priced at
    # the first window fold). Snapshot, not flush: the instrumented
    # window must not fold into the steady-state step-time histogram.
    tel = engine.telemetry_snapshot()
    spans = {k.split("span/", 1)[1]: v
             for k, v in tel["histograms"].items() if k.startswith("span/")}
    telemetry = {
        "step_time_s": tel["histograms"].get("train/step_time_s", {}),
        "spans": spans,
        "mfu_engine_pct": round(tel["gauges"].get("train/mfu", 0.0) * 100,
                                2),
        "tokens_per_sec_engine": round(
            tel["gauges"].get("train/tokens_per_sec", 0.0), 1),
        "flops_per_step_cost_analysis": tel["gauges"].get(
            "train/flops_per_step", 0.0),
    }

    # free the ~8 GB of training state before later sections allocate
    # their params + KV caches (same ordering rule as the BERT section)
    del engine, model, loss
    import jax as _jax
    _jax.clear_caches()
    return {
        "mfu_pct": round(mfu * 100, 2),
        "samples_per_sec_per_chip": round(samples_per_sec, 2),
        "tokens_per_sec": round(tokens_per_step / dt, 1),
        "step_time_ms": round(dt * 1000, 2),
        "achieved_tflops": round(achieved / 1e12, 2),
        "device": getattr(dev, "device_kind", str(dev)),
        # loss after ~92 optimizer steps on ONE repeated batch — a
        # memorization sanity value, not a convergence claim (see r4
        # note: window growth tripled the steps before this read).
        "loss": final_loss,
        "loss_note": "after ~92 steps on one repeated batch",
        # SURVEY §7 memory evidence: exact XLA buffer assignment of
        # the train step (device.memory_stats is unavailable through
        # tunneled backends). True peak is BELOW the sum of these two
        # — donated state buffers are reused for temporaries — and
        # bounded by the 15.75 GB the chip actually has (the step
        # runs). Max params/chip: 1.558B trains on this 16 GB chip
        # via ZeRO-Offload — the "gpt2_xl" entry is that evidence run.
        "hbm_compiled_buffers_gb": {
            "state_and_batch": round(mem["argument_bytes"] / 2**30, 2),
            "activations_and_temps": round(mem["temp_bytes"] / 2**30, 2),
        },
        "dense_params_b": params_b,
        # instrumented-mode per-phase means, NET of the per-phase
        # readback fence (the 'fence' entry is the measured pure RTT —
        # ~100 ms through this tunnel). The headline step_time_ms is the
        # fused program with its window fence amortized out the same way.
        "phase_breakdown_ms": phase_ms,
        "tunnel_fence_ms_per_readback": round(fence_s * 1000, 1),
        # unified telemetry (ISSUE 4): per-phase span times, step-time
        # percentiles over the timed windows, and the engine's own MFU
        # gauge next to the bench's analytic headline
        "telemetry": telemetry,
    }


def _run_proxy_bench(script_relpath, devices=8, timeout=900):
    """Run a tests/perf bench script as an N-virtual-device CPU
    subprocess (XLA_FLAGS is read at interpreter start, so the parent
    process cannot widen its own device count) and parse its JSON
    output. The script prints one indented JSON object; log lines may
    precede it, so parse from the last bare "{" line onward."""
    import subprocess
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count"
                          f"={devices}")
    proc = subprocess.run(
        [sys.executable, os.path.join(here, *script_relpath.split("/"))],
        env=env, cwd=here, capture_output=True, text=True,
        timeout=timeout)
    if proc.returncode != 0:
        return {"skipped": f"proxy subprocess rc={proc.returncode}: "
                           f"{(proc.stderr or '')[-200:]}"}
    lines = (proc.stdout or "").splitlines()
    try:
        start = max(i for i, l in enumerate(lines) if l.strip() == "{")
        out = json.loads("\n".join(lines[start:]))
    except (ValueError, json.JSONDecodeError) as e:
        return {"skipped": f"proxy output unparseable: {e}"}
    return {"mesh": f"cpu_virtual_{devices}dev_step_time_proxy", **out}


def bench_zero3_prefetch():
    """``stage3_prefetch`` on vs off (tests/perf/prefetch_bench.py).

    The prefetch pipeline needs a >1-device data axis. On a multi-chip
    claim it runs in-process against the real mesh; on the usual
    single-chip harness it spawns the 8-virtual-device CPU proxy in a
    subprocess — a step-time proxy that exercises the exact train
    program, honestly labeled."""
    import jax
    if len(jax.devices()) > 1:
        from tests.perf.prefetch_bench import run_prefetch_bench
        return {"mesh": "real", **run_prefetch_bench()}
    return _run_proxy_bench("tests/perf/prefetch_bench.py")


def bench_zero3_hier():
    """Link-aware ZeRO-3 prefetch stream (ISSUE 16,
    tests/perf/zero3_hier_bench.py): flat single-ring stage-3 stream vs
    the two-level reschedule vs two-level + compressed grad hop, one
    prefetch engine each on a 2 x (n/2) synthetic split. Headline gate
    is ``inter_bytes_reduction`` — modeled FLAT-ring slow-hop bytes
    over the compressed two-level schedule's (acceptance: >= 2x; note
    the denominator is the flat baseline, not the same-schedule fp32
    figure onebit_comm uses). Step times recorded for calibration; the
    wire-byte ledger is the portable claim on this CPU proxy."""
    import jax
    if len(jax.devices()) >= 4 and len(jax.devices()) % 2 == 0:
        from tests.perf.zero3_hier_bench import run_zero3_hier_bench
        return {"mesh": "real", **run_zero3_hier_bench()}
    return _run_proxy_bench("tests/perf/zero3_hier_bench.py")


def bench_onebit_comm():
    """Hierarchical link-aware 1-bit gradient exchange (ISSUE 10,
    tests/perf/onebit_comm_bench.py): flat compressed allreduce vs the
    two-level split (fast axis uncompressed, slow axis sign-packed) vs
    the exact two-level mean, one OneBitAdam engine each. Headline gate
    is ``bytes_reduction`` — modeled post-freeze slow-hop fp32 bytes
    over sign-packed bytes, exact because the bucket plan and policy
    are static (acceptance: >= 4x). Step times recorded for
    calibration; on the CPU proxy the links are memcpys, so wall-clock
    is not the portable claim — the wire-byte ledger is."""
    import jax
    if len(jax.devices()) >= 4 and len(jax.devices()) % 2 == 0:
        from tests.perf.onebit_comm_bench import run_onebit_comm_bench
        return {"mesh": "real", **run_onebit_comm_bench()}
    return _run_proxy_bench("tests/perf/onebit_comm_bench.py")


def bench_serving():
    """Continuous batching vs the static-batch path on a mixed-length
    Poisson workload (tests/perf/serving_bench.py): requests/sec +
    decode tokens/sec for both systems and the speedup. Uses the bench
    module's default model sizing (CPU-safe); the paged engine itself is
    exercised at GPT-2-large scale by the decode section's configs."""
    from tests.perf.serving_bench import run_serving_bench
    out = run_serving_bench()
    tel = out["continuous"].get("telemetry", {})
    return {
        "requests_per_sec_continuous":
            out["continuous"]["requests_per_sec"],
        "requests_per_sec_static": out["static"]["requests_per_sec"],
        "decode_tokens_per_sec_continuous":
            out["continuous"]["decode_tokens_per_sec"],
        "decode_tokens_per_sec_static":
            out["static"]["decode_tokens_per_sec"],
        "speedup_requests_per_sec": out["speedup_requests_per_sec"],
        "mean_slot_occupancy": out["continuous"]["mean_slot_occupancy"],
        # serving telemetry headline numbers + the full snapshot
        "ttft_p50_s": tel.get("ttft_s", {}).get("p50"),
        "ttft_p99_s": tel.get("ttft_s", {}).get("p99"),
        "page_pool_occupancy_hwm": tel.get(
            "page_pool", {}).get("occupancy_hwm"),
        # watchdog verdict next to the percentiles (ISSUE 6): nonzero
        # trips mean the winning window was NOT clean — read the dump
        "watchdog_trips": sum(
            ((tel.get("watchdog") or {}).get("trips") or {}).values()),
        "watchdog_dump_id": tel.get("dump_id", 0),
        "watchdog_last_anomaly": (tel.get("last_anomaly") or {}).get(
            "rule"),
        "telemetry": tel,
        "workload": out["workload"],
    }


def bench_serving_hot_prefix():
    """Hot-prefix serving workload (ISSUE 9): N requests sharing an
    S-token system prompt, prefix cache off vs on. The headline gate is
    ``prefix_hit_rate`` (token-level: shared prompt tokens whose pages
    AND prefill compute were skipped); pages-saved, COW hits and the
    TTFT shift ride along. ``token_mismatches`` must be 0 — sharing may
    never change outputs."""
    from tests.perf.serving_bench import run_hot_prefix_bench
    return run_hot_prefix_bench()


def bench_serving_spec_decode():
    """Speculative decoding at b1 (ISSUE 9): plain engine vs n-gram
    self-drafting + one-dispatch multi-query verification, greedy,
    outputs asserted token-for-token identical. Headline gate:
    ``spec_decode_speedup`` (tok/s ratio). The CPU proxy sits in the
    dispatch-bound regime the real chip's b1 decode also lives in
    (BENCH_r05: 95 tok/s llama7b-b1 was one model call per token)."""
    from tests.perf.serving_bench import run_spec_decode_bench
    return run_spec_decode_bench()


def bench_serving_elastic():
    """Elastic preemption-tolerant serving (ISSUE 11): a Poisson trace
    through a 3-replica pool taking one injected hard kill + one
    graceful drain, both recovered from committed elastic snapshots
    (headline gate: ``recovered_fraction`` must stay 1.0;
    ``committed_token_loss`` must be 0 — greedy replay regenerates the
    identical streams), plus TTFT p99 under a burst overload with the
    watchdog-trip autoscaler on vs off."""
    from tests.perf.serving_bench import run_serving_elastic_bench
    return run_serving_elastic_bench()


def bench_serving_disagg():
    """Disaggregated prefill/decode serving (ISSUE 14): the BENCH_r08
    mixed-traffic trace served colocated vs through the DisaggRouter
    (prefill-role + decode-role engines, in-process page-handoff
    transport). Headline gate: ``ttft_p99_s_disagg`` (lower is better
    — prompt admission decoupled from decode slot residency); the
    colocated leg, the attribution breakdown, token parity and the
    page-pool leak fence ride the detail.

    Since r16 the section grows a ``transport: "process"`` leg
    (ISSUE 17): the same roles split across 2 REAL ranked OS
    processes, KV pages moving as versioned wire frames through the
    gloo host-bytes collective. Its headline gate is
    ``ttft_p99_s_disagg_xproc``; byte counters, the transport_s
    attribution and the cross-process parity/leak fences ride the
    ``xproc`` detail.

    Since r18 the scale-out leg (ISSUE 18): the identical trace over
    world=3 (2 decode ranks, targeted addressed frames, LPT
    balancing). Headline gate: ``decode_scaleout_tok_s_ratio``
    (world-3 aggregate decode tok/s over world-2's single rank,
    higher is better, ~2x when the balancer holds per-rank occupancy);
    the per-handoff wire-cost figures for both worlds, slot
    utilization per role, and the per-rank delivery split ride the
    ``xproc``/``xproc_w3`` details. The scale-out legs run a
    saturation geometry (16 reqs x 24 new tokens) so both world-3
    decode ranks hold single-rank slot occupancy; the ``xproc`` TTFT
    leg keeps the BENCH_r16 geometry (32 x 6) so
    ``ttft_p99_s_disagg_xproc`` stays comparable across runs."""
    from tests.perf.serving_bench import (run_disagg_bench,
                                          run_disagg_scaleout_bench,
                                          run_disagg_xproc_bench)
    out = run_disagg_bench()
    out["xproc"] = xp = run_disagg_xproc_bench()
    sc = run_disagg_scaleout_bench()
    out["xproc_w2_scaleout"] = sc["xproc_w2"]
    out["xproc_w3"] = sc["xproc_w3"]
    out["ttft_p99_s_disagg_xproc"] = xp["ttft_p99_s_disagg_xproc"]
    out["decode_scaleout_tok_s_ratio"] = \
        sc["decode_scaleout_tok_s_ratio"]
    out["wire_cost_ratio_w3_over_w2"] = sc["wire_cost_ratio_w3_over_w2"]
    return out


def bench_fault_recovery():
    """Fault-tolerant training supervisor MTTR (ISSUE 15): one
    SIGKILLed rank in a 2-process world under the
    runtime/elastic/supervisor.py state machine, measured with stdlib
    workers so the section prices the RECOVERY machinery (detect →
    teardown → backoff → respawn → first step), not an engine compile
    — the end-to-end engine legs are pinned by the slow
    tests/test_fault_tolerance.py acceptance tests. Reported:
    ``detect_s`` (rank death → supervisor incident record) and
    ``restart_to_first_step_s`` (death → the restarted epoch's first
    step line, the MTTR minus the resumed engine's compile)."""
    import sys
    import tempfile
    import textwrap
    import time as _time
    from deepspeed_tpu.runtime.elastic.supervisor import Supervisor
    from deepspeed_tpu.telemetry.recorder import FlightRecorder

    d = tempfile.mkdtemp(prefix="fault_recovery_")
    worker = os.path.join(d, "worker.py")
    with open(worker, "w") as fh:
        fh.write(textwrap.dedent("""
            import os, signal, time
            rank = int(os.environ["DSTPU_PROCESS_ID"])
            epoch = int(os.environ["DSTPU_RESTART_EPOCH"])
            print(f"FIRST_STEP {time.time()}", flush=True)
            if epoch == 0 and rank == 1:
                time.sleep(0.3)
                print(f"DYING {time.time()}", flush=True)
                os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(0.8)          # the rest of the "epoch"
        """))
    rec = FlightRecorder()
    sup = Supervisor([sys.executable, worker], 2,
                     heartbeat_dir=os.path.join(d, "hb"),
                     grace_kill_s=2.0, max_restarts=2,
                     backoff_base_s=0.2, backoff_max_s=0.5,
                     poll_s=0.05, recorder=rec)
    t0 = _time.time()
    rc = sup.run(deadline_s=60)
    wall_s = _time.time() - t0
    if rc != 0 or sup.restarts != 1:
        return {"skipped": f"unexpected supervision outcome rc={rc} "
                           f"restarts={sup.restarts}"}

    import re
    def stamp(path, tag):
        m = re.search(rf"{tag} ([0-9.]+)", open(path).read())
        return float(m.group(1)) if m else None
    t_die = stamp(sup.log_paths[(0, 1)], "DYING")
    t_up = stamp(sup.log_paths[(1, 0)], "FIRST_STEP")
    t_detect = next(ev["ts"] for ev in rec.events()
                    if ev["kind"] == "rank_exit")
    t_respawn = next(ev["ts"] for ev in rec.events()
                     if ev["kind"] == "supervisor_spawn"
                     and ev.get("restart_epoch") == 1)
    return {
        "world": 2,
        "detect_s": round(t_detect - t_die, 4),
        "teardown_respawn_s": round(t_respawn - t_detect, 4),
        "restart_to_first_step_s": round(t_up - t_die, 4),
        "supervision_wall_s": round(wall_s, 3),
        "poll_s": sup.poll_s,
        "grace_kill_s": sup.grace_kill_s,
        "note": "stdlib workers: MTTR of the supervisor machinery; "
                "engine resume cost = compile + snapshot load, pinned "
                "by the slow acceptance tests",
    }


def bench_sparse_attention(jnp):
    """Block-sparse vs dense-flash attention, fwd+bwd (the reference's
    sparse-attention headline: up to 6.1x on GPT-2 and 10x longer
    sequences, 2020-09-09 blog). BigBird (1 random + 3 window + 1 global
    block) at each sequence's measured-best layout block size — the
    kernel is DMA-ISSUE bound (~1.4 us per tile copy) with the r5
    grouped-row fusion amortizing the issue cost across R fused q-block
    rows per union tile. r5 sweep (tests/perf/bs_sweep_r5.py, grouped):
    S=4096 -> 1.08x/0.93x/1.36x at block 128/256/512; S=16384 ->
    2.30x/2.62x/2.75x — both cases run block 512. Near-dense layouts
    auto-fall back to the masked-dense path (the calibrated crossover in
    sparse_self_attention._kernel_beats_dense)."""
    import time
    import jax
    from deepspeed_tpu.ops.sparse_attention import BigBirdSparsityConfig
    from deepspeed_tpu.ops.pallas.blocksparse import blocksparse_attention
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

    out = {}
    H, D = 16, 64
    for S, B, block in ((4096, 4, 512), (16384, 1, 512)):
        cfg = BigBirdSparsityConfig(num_heads=1, block=block,
                                    num_random_blocks=1,
                                    num_sliding_window_blocks=3,
                                    num_global_blocks=1)
        np.random.seed(0)
        layout = cfg.make_layout(S)
        density = float(layout[0].mean())
        rng = jax.random.PRNGKey(0)
        q, k, v = (jax.random.normal(jax.random.fold_in(rng, i),
                                     (B, H, S, D), jnp.bfloat16) * 0.3
                   for i in range(3))

        def sp_loss(q, k, v):
            return jnp.sum(blocksparse_attention(
                q, k, v, layout, block).astype(jnp.float32) ** 2)

        def dn_loss(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=False).astype(jnp.float32) ** 2)

        def timed(fn):
            g = jax.jit(jax.grad(fn, argnums=(0, 1, 2)))
            r = g(q, k, v)
            float(jax.device_get(r[0].astype(jnp.float32).sum()))  # fence
            t0 = time.perf_counter()
            for _ in range(5):
                r = g(q, k, v)
            float(jax.device_get(r[0].astype(jnp.float32).sum()))
            return (time.perf_counter() - t0) / 5

        sp = timed(sp_loss)
        dn = timed(dn_loss)
        out[f"S{S}"] = {"sparse_ms": round(sp * 1000, 2),
                        "dense_flash_ms": round(dn * 1000, 2),
                        "speedup": round(dn / sp, 2),
                        "layout_block": block,
                        "layout_density": round(density, 3)}
    out["crossover_note"] = (
        "kernel is DMA-issue bound; speedup ~ 1/active_block_count. "
        "Auto mode falls back to masked-dense when the calibrated "
        "estimate predicts the kernel loses (near-dense layouts)")
    return out


def bench_decode(jnp):
    """GPT-2 large KV-cache decode tokens/sec. b1 at 2k context is the
    latency case; b32 uses a 512 context because 36 layers of bf16 KV at
    2k x 32 is ~12 GB (~24 GB with the scan carry's double buffer — past a
    16 GB chip either way once params/activations are resident)."""
    import time
    import jax
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.models.gpt2_inference import (
        generate, convert_gpt2_params, quantize_gpt2_inference_params)
    out = {}
    cases = (
        # latency case: scan decode (one dispatch for the whole loop)
        ("b1_ctx2048", 1, 2048, dict(scan_decode=True)),
        # latency case, int8 weights + int8 KV (head-major cache): the
        # serving recipe — weight reads and cache reads both halve
        ("b1_ctx2048_int8", 1, 2048,
         dict(scan_decode=True, quantize_bits=8, kv_cache_bits=8)),
        # throughput, bf16 cache: ~6 GB of KV can't afford the scan
        # carry's double buffer, so per-token step loop
        ("b32_ctx512", 32, 512, dict(scan_decode=False)),
        # throughput, int8 KV cache: the halved cache fits the scan path
        # — the two serving features composing (2.1x over the step loop)
        ("b32_ctx512_int8kv", 32, 512,
         dict(scan_decode=True, kv_cache_bits=8)),
    )
    for name, bs, ctx, kw in cases:
        cfg = GPT2Config(vocab_size=50304, n_positions=ctx, n_embd=1280,
                         n_layer=36, n_head=20, dtype=jnp.bfloat16,
                         param_dtype=jnp.bfloat16, scan_layers=True)
        rng = np.random.RandomState(0)
        prompt = rng.randint(0, 50304, size=(bs, ctx - 80)).astype(np.int32)
        params = jax.jit(GPT2LMHeadModel(cfg).init)(
            jax.random.PRNGKey(0), prompt[:, :8])["params"]
        if kw.get("quantize_bits"):
            params = quantize_gpt2_inference_params(
                convert_gpt2_params(params, cfg))

        def run(new):
            toks = generate(cfg, params, prompt, max_new_tokens=new,
                            max_out_tokens=ctx, **kw)
            return float(jax.device_get(toks[0, -1]))

        run(4)                      # compile both lengths before timing
        run(68)
        # best of three difference-method windows: single samples swing
        # ±10% through the tunnel (same reasoning as the headline's
        # 3-window rule — report the machine, not the tunnel)
        best_dt, t_short = float("inf"), 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            run(4)
            t_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            run(68)
            t_l = time.perf_counter() - t0
            # prompt pass and fixed overheads cancel in the difference
            if t_l - t_s < best_dt:
                best_dt, t_short = t_l - t_s, t_s
        decode_tps = bs * 64 / best_dt
        out[name] = {"decode_tokens_per_sec": round(decode_tps, 1),
                     "prompt_plus_4_tokens_s": round(t_short, 3)}
        del params, run   # run's closure pins params otherwise
        jax.clear_caches()
    return out


def bench_llama_decode(jnp, bs=1, ctx=2048):
    """LLaMA-7B int8 serving through the fused RMS/SwiGLU/stacked-kernel
    loop (models/llama_inference.py). Weights are random int8 codes —
    decode reads exactly the bytes a converted checkpoint would, without
    materializing 13.5 GB of bf16 first. ROOFLINE: 6.74B int8 params =
    6.7 GB of weight reads per tick, so b1 is bounded at ~120 tok/s on
    an 819 GB/s chip no matter the software; batching shares the weight
    read across rows (the b8 case)."""
    import time
    import jax
    from deepspeed_tpu.models.llama import llama_7b
    from deepspeed_tpu.models.llama_inference import (
        llama_fast_generate, random_int8_serving_params)
    cfg = llama_7b(dtype=jnp.bfloat16, param_dtype=jnp.bfloat16,
                   max_seq_len=ctx)
    sparams = random_int8_serving_params(cfg)
    rs = np.random.RandomState(0)
    prompt = rs.randint(0, cfg.vocab_size,
                        size=(bs, ctx - 80)).astype(np.int32)

    def run(new):
        toks = llama_fast_generate(cfg, sparams, prompt,
                                   max_new_tokens=new,
                                   max_out_tokens=ctx, kv_cache_bits=8)
        return float(jax.device_get(toks[0, -1]))

    run(4)
    run(68)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        run(4)
        t_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        run(68)
        t_l = time.perf_counter() - t0
        best = min(best, t_l - t_s)
    return {"decode_tokens_per_sec": round(bs * 64 / best, 1),
            "params_b": round(cfg.num_params() / 1e9, 2),
            "weight_read_bound_tok_s_b1": 122}


def bench_moe(dstpu, make_mesh, MeshConfig, dev, batch_size=8, seq=512):
    """Expert-parallel MoE GPT-2 training throughput on one chip —
    8 experts, top-1 routing (the beyond-reference MoE subsystem's only
    perf line; regressions in the routing einsums show here)."""
    import time
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    cfg_m = GPT2Config(vocab_size=50304, n_positions=seq, n_embd=512,
                       n_layer=8, n_head=8, dtype=jnp.bfloat16,
                       scan_layers=True, moe_experts=8, moe_k=1)
    cfg = {
        "train_batch_size": batch_size,
        "bf16": {"enabled": True},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = dstpu.initialize(
        config=cfg, model=GPT2LMHeadModel(cfg_m),
        mesh=make_mesh(MeshConfig(data=1), devices=[dev]))
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(
        0, 50304, size=(batch_size, seq)).astype(np.int32)}
    for _ in range(2):
        loss = engine.train_batch(batch)
    float(jax.device_get(loss))
    iters = 8
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = engine.train_batch(batch)
    final = float(jax.device_get(loss))
    dt = (time.perf_counter() - t0) / iters
    return {"samples_per_sec": round(batch_size / dt, 1),
            "tokens_per_sec": round(batch_size * seq / dt, 1),
            "experts": 8, "loss": round(final, 3)}


INF9B_WARM_SENTINEL = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".jax_cache",
    "inf9b_warmed")


def tiled_gpt2_init(cfg, seed=0):
    """Fast tiled-random GPT-2 init: every stacked layer shares one
    random block (the canonical copy — bench + tests/perf harnesses
    import this). Loss still falls because per-layer gradients differ
    from step one; avoids minutes of gaussians per GB on 1-core hosts."""
    import jax
    from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel
    shapes = jax.eval_shape(
        GPT2LMHeadModel(cfg).init, jax.random.PRNGKey(0),
        np.zeros((1, 8), np.int32))["params"]
    rs = np.random.RandomState(seed)

    def leaf(path, s):
        names = [str(getattr(p, "key", p)) for p in path]
        if s.ndim == 3:          # scan-stacked [L, ...]: tile one layer
            one = (rs.standard_normal(s.shape[1:]).astype(np.float32)
                   / np.sqrt(max(s.shape[-2], 1))
                   if names[-1] == "kernel"
                   else np.zeros(s.shape[1:], np.float32))
            a = np.broadcast_to(one, s.shape)
        elif names[-1] in ("wte", "wpe"):
            a = rs.standard_normal(s.shape).astype(np.float32) * 0.02
        elif names[-1] == "scale":
            a = np.ones(s.shape, np.float32)
        else:
            a = np.zeros(s.shape, np.float32)
        return a.astype(np.dtype(s.dtype))
    import jax.tree_util as jtu
    return jtu.tree_map_with_path(leaf, shapes)


def bench_infinity_6b(dstpu, dev, steps=3):
    """THE scale proof: a multi-billion-param GPT-2 trains on this one
    16 GB chip (ZeRO-Infinity, runtime/zero/infinity.py) — compute
    params resting on NVMe, fp32 master + Adam moments in pinned_host,
    per-segment streamed fwd/bwd/update. Reference claim this answers:
    40B on a 32 GB V100 (ZeRO-Infinity blog, 1.25 B/GB).

    Two proven sizes: 6.25B (61 GB pinned state, 0.39 B/GB) and 9.41B
    (94 GB pinned, 0.59 B/GB — measured: loss 11.77 -> 10.06, 18.6 s
    steps, flat RSS). The 9.4B config runs when its compile cache is
    warm (sentinel, same pattern as the XL case) so a cold driver run
    isn't charged its ~19-minute first compile; otherwise 6.25B.

    Init is TILED-random (every layer shares one random block): the
    bench measures the streaming engine, not 6.25 s of gaussians per GB
    on a 1-core host; loss still falls because gradients differ per
    layer from step one."""
    import shutil
    import time
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    def rss_mb():
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    return int(line.split()[1]) / 1024
        return 0.0

    big = os.path.exists(INF9B_WARM_SENTINEL) \
        or os.environ.get("DSTPU_BENCH_FORCE_9B")
    E, L, H = (4608, 36, 36) if big else (4096, 30, 32)
    cfg_m = GPT2Config(vocab_size=50304, n_positions=1024, n_embd=E,
                       n_layer=L, n_head=H, dtype=jnp.bfloat16,
                       param_dtype=jnp.bfloat16, scan_layers=True,
                       remat=True, loss_chunk=2048)
    segments = 6
    t0 = time.time()
    params = tiled_gpt2_init(cfg_m)
    init_s = time.time() - t0

    nvme = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".bench_nvme_6b")
    shutil.rmtree(nvme, ignore_errors=True)
    os.makedirs(nvme, exist_ok=True)
    try:
        t0 = time.time()
        engine, _, _, _ = dstpu.initialize(
            config={
                "train_batch_size": 4,
                "zero_optimization": {
                    "stage": 3,
                    "offload_param": {"device": "nvme", "nvme_path": nvme,
                                      "stream_segments": segments},
                    "offload_optimizer": {"device": "cpu"}},
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            },
            model=GPT2LMHeadModel(cfg_m), model_parameters=params)
        del params
        setup_s = time.time() - t0
        rng = np.random.RandomState(0)
        batch = {"input_ids": rng.randint(
            0, 50304, size=(4, 1024)).astype(np.int32)}
        t0 = time.time()
        l0 = engine.train_batch(batch)
        compile_step_s = time.time() - t0
        rss0 = rss_mb()
        ts, losses = [], [l0]
        for _ in range(steps):
            t0 = time.time()
            losses.append(engine.train_batch(batch))
            ts.append(time.time() - t0)
        return {
            "params_b": round(cfg_m.num_params() / 1e9, 3),
            "params_on_disk_mb": round(
                engine.params_on_disk_bytes() / 2**20, 1),
            "steady_step_s": round(min(ts), 2),
            "first_loss": round(losses[0], 3),
            "last_loss": round(losses[-1], 3),
            "host_rss_growth_mb_over_steps": round(rss_mb() - rss0, 1),
            "init_s": round(init_s, 1), "setup_s": round(setup_s, 1),
            "first_step_incl_compile_s": round(compile_step_s, 1),
            "hbm_gb": 16, "params_per_hbm_gb": round(
                cfg_m.num_params() / 1e9 / 16, 3),
        }
    except Exception as e:
        return {"skipped": str(e)[:300]}
    finally:
        shutil.rmtree(nvme, ignore_errors=True)


def warm_infinity_9b():
    """One bench-path 9.4B run to warm its compile cache; the sentinel
    is written ONLY after the run succeeds (an interrupted warm must
    not leave later bench runs selecting the 9.4B config against a
    cold cache — the config is forced via env during warming)."""
    import jax
    import deepspeed_tpu as dstpu
    _enable_compile_cache()
    os.environ["DSTPU_BENCH_FORCE_9B"] = "1"
    try:
        out = bench_infinity_6b(dstpu, jax.devices()[0], steps=2)
    finally:
        os.environ.pop("DSTPU_BENCH_FORCE_9B", None)
    if "skipped" not in out:
        open(INF9B_WARM_SENTINEL, "w").write(json.dumps(out))
    print(json.dumps(out))
    return out


def bench_elastic_ckpt(dstpu, make_mesh, MeshConfig, dev):
    """Async-snapshot overhead (ISSUE 7 acceptance): steady-state step
    time of a small GPT-2 run (a) with no checkpointing, (b) with an
    async snapshot every ``interval`` (4) steps — deliberately tight so
    the per-snapshot cost is measurable above step noise; begin stages
    + submits on the write-behind aio handle, the commit fence rides
    the next step boundary — and (c) the measured blocking
    engine.save_checkpoint stall the async path replaces. Embeds the
    sync-free telemetry counters (ckpt/bytes_written, ckpt/stall_s)
    the engine kept."""
    import shutil
    import tempfile
    import time
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.telemetry import default_registry

    cfg_m = GPT2Config(vocab_size=2048, n_positions=128, n_embd=256,
                       n_layer=4, n_head=4, dtype=jnp.float32,
                       scan_layers=True)
    steps = 8
    interval = 4
    tmp = tempfile.mkdtemp(prefix="dstpu_elastic_ckpt_")
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, 2048, size=(4, 128))
             .astype(np.int32)}

    def run(tagdir, snapshot=False, fsync=False, o_direct=False):
        cfg = {
            "train_batch_size": 4,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "steps_per_print": 1000,
            "aio": {"o_direct": bool(o_direct)},
        }
        if snapshot:
            cfg["snapshot"] = {"path": os.path.join(tmp, tagdir),
                               "interval_steps": interval, "keep": 2,
                               "fsync": fsync}
        default_registry().reset()
        engine, _, _, _ = dstpu.initialize(
            config=cfg, model=GPT2LMHeadModel(cfg_m),
            mesh=make_mesh(MeshConfig(data=1), devices=[dev]))
        engine.train_batch(batch)        # compile
        engine.telemetry.reset()
        ts = []
        for _ in range(steps):
            t0 = time.perf_counter()
            engine.train_batch(batch)
            ts.append(time.perf_counter() - t0)
        # commit the possibly in-flight final-step snapshot BEFORE the
        # teardown rmtree races its aio writes (and so both begun
        # snapshots have a measured commit fence)
        engine.finalize_pending_snapshot()
        snap = engine.telemetry.snapshot("ckpt/")
        if engine._preemption is not None:
            engine._preemption.restore()
        return engine, sum(ts) / len(ts), snap

    try:
        eb, base_s, _ = run("never")
        t0 = time.perf_counter()
        eb.save_checkpoint(os.path.join(tmp, "blocking"))
        blocking_s = time.perf_counter() - t0
        # fsync OFF is the apples-to-apples overhead number (the
        # blocking save above never fsyncs either); the fsync-fenced
        # variant prices the durability barrier separately
        ea, async_s, snap = run("snaps", snapshot=True, fsync=False)
        _, async_fsync_s, _ = run("snaps_fsync", snapshot=True,
                                  fsync=True)
        # fsync honesty (ISSUE 20): the fsync price above is a BUFFERED
        # price (per-fd data flush out of the page cache); under
        # O_DIRECT the data is on-device at the drain and the remaining
        # fsync is metadata-only — the delta between these two
        # fsync-fenced runs is what the page cache was hiding
        _, async_direct_fsync_s, _ = run("snaps_direct", snapshot=True,
                                         fsync=True, o_direct=True)
        stall = snap["histograms"].get("ckpt/stall_s", {})
        n_snaps = max(int(snap["counters"].get("ckpt/snapshots", 0)), 1)
        bytes_per = snap["counters"].get("ckpt/bytes_written", 0) / n_snaps
        return {
            "step_s_base": round(base_s, 3),
            "step_s_async_ckpt": round(async_s, 3),
            "async_overhead_pct": round((async_s / base_s - 1) * 100, 1),
            "per_snapshot_overhead_s": round(
                (async_s - base_s) * steps / n_snaps, 3),
            # the acceptance-criterion number: the bench snapshots every
            # `interval` steps to make the per-snapshot cost measurable;
            # at the production default cadence (interval_steps=100) the
            # same cost amortizes to this share of step time
            "overhead_pct_at_interval_100": round(
                max(async_s - base_s, 0) * steps / n_snaps
                / (100 * base_s) * 100, 2),
            "step_s_async_ckpt_fsync": round(async_fsync_s, 3),
            "step_s_async_ckpt_fsync_o_direct": round(
                async_direct_fsync_s, 3),
            # per-snapshot durability-barrier price, both modes: what
            # fsync adds over the unfenced async run, amortized per
            # snapshot (buffered pays a data flush; direct pays only
            # the dirent/metadata flush)
            "fsync_overhead_s_per_snapshot_buffered": round(
                max(async_fsync_s - async_s, 0) * steps / n_snaps, 3),
            "fsync_overhead_s_per_snapshot_o_direct": round(
                max(async_direct_fsync_s - async_s, 0) * steps
                / n_snaps, 3),
            "blocking_save_s": round(blocking_s, 3),
            "blocking_share_if_per_interval_pct": round(
                blocking_s / (interval * base_s) * 100, 1),
            "ckpt_mb_per_snapshot": round(bytes_per / 2**20, 1),
            "ckpt_stall_s_mean": round(stall.get("mean", 0.0), 4),
            "ckpt_stall_s_max": round(stall.get("max", 0.0), 4),
            "snapshot_interval_steps": interval,
            "snapshots_per_run": n_snaps,
            "note": "overhead = host staging (d2h+memcpy+crc32) of the "
                    "full state; the aio writes + commit fence overlap "
                    "the next step (ckpt_stall_s is what the fence "
                    "actually blocked). CPU-harness caveat: the 2-core "
                    "box charges the staging AND the overlapped disk "
                    "writes to the same cores as compute — on a TPU "
                    "host the step is device-bound and the staging "
                    "share shrinks by the step-time ratio.",
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_nvme_param_tier(dstpu, make_mesh, MeshConfig, dev):
    """offload_param device=nvme evidence, blocking vs PIPELINED (PR 5):
    a small GPT-2 trains with its params resting on disk between steps,
    once with the r5 blocking park/unpark and once with the pipelined
    swap schedule (pipeline_read + pipeline_write + write-behind cache).
    Reports both steady step times, loss-trajectory equality, the
    sync-free swap telemetry (stall seconds hidden vs exposed, phase
    times), and a swap-cycle microbench on the same parameter set that
    isolates the tier's own cost from the model arithmetic (on a
    CPU-only harness the step is compute-bound, so the cycle number is
    the tier's honest speedup; on the r5 tunnel harness the step itself
    was swap-bound)."""
    import glob
    import tempfile
    import time
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.telemetry import default_registry

    def rss_mb():
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    return int(line.split()[1]) / 1024
        return 0.0

    cfg_m = GPT2Config(vocab_size=8192, n_positions=256, n_embd=512,
                       n_layer=8, n_head=8, dtype=jnp.bfloat16,
                       scan_layers=True)
    steps = 3

    def train_run(pipelined, o_direct=False):
        tmp = tempfile.mkdtemp(prefix="dstpu_nvme_param_")
        off = {"device": "nvme", "nvme_path": tmp}
        if pipelined:
            off.update({"pipeline_read": True, "pipeline_write": True,
                        "buffer_count": 4})
        cfg = {
            "train_batch_size": 4,
            "zero_optimization": {
                "stage": 2, "offload_param": off,
                "offload_optimizer": {"device": "cpu"}},
            "bf16": {"enabled": True},
            "aio": {"o_direct": bool(o_direct)},
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "steps_per_print": 1000,
        }
        try:
            default_registry().reset()
            engine, _, _, _ = dstpu.initialize(
                config=cfg, model=GPT2LMHeadModel(cfg_m),
                mesh=make_mesh(MeshConfig(data=1), devices=[dev]))
            rng = np.random.RandomState(0)
            batch = {"input_ids": rng.randint(0, 8192, size=(4, 256))
                     .astype(np.int32)}
            l0 = float(engine.train_batch(batch))
            engine.telemetry.reset()
            rss0 = rss_mb()
            ts = []
            for _ in range(steps):
                t0 = time.perf_counter()
                l1 = float(engine.train_batch(batch))
                ts.append(time.perf_counter() - t0)
            snap = engine.telemetry.snapshot("swap/")
            disk = sum(os.path.getsize(p) for p in glob.glob(
                tmp + "/param_swap_*/param_*.swp"))
            parked = all(leaf.is_deleted() for leaf in
                         jax.tree_util.tree_leaves(engine.state.params))
            hist = snap["histograms"]
            counters = snap["counters"]
            step_s = min(ts)
            stall_sum = hist.get("swap/stall_s", {}).get("sum", 0.0)
            stall_per_step = stall_sum / steps
            return {
                "steady_step_s": round(step_s, 3),
                "first_loss": l0, "last_loss": l1,
                "parked": bool(parked),
                "disk_mb": round(disk / 2**20, 1),
                "rss_growth_mb": round(rss_mb() - rss0, 1),
                "stall_s_per_step": round(stall_per_step, 3),
                # matching statistics: total stall over total wall of the
                # SAME steps (min-step denominators overstate the share
                # on a ±20%-noise harness)
                "stall_share_of_step": round(stall_sum / sum(ts), 3),
                "unpark_s": round(hist.get("swap/unpark_s", {})
                                  .get("mean", 0.0), 3),
                "park_s": round(hist.get("swap/park_s", {})
                                .get("mean", 0.0), 3),
                "bytes_read_mb_per_step": round(
                    counters.get("swap/bytes_read", 0) / steps / 2**20, 1),
                "cache_hit_mb_per_step": round(
                    counters.get("swap/cache_hit_bytes", 0) / steps
                    / 2**20, 1),
                "bytes_written_mb_per_step": round(
                    counters.get("swap/bytes_written", 0) / steps
                    / 2**20, 1),
                # device-side bandwidth gauges: set by the alignment
                # layer over DIRECT bytes only, so buffered runs report 0
                "device_read_mb_s": snap["gauges"].get(
                    "swap/device_read_mb_s", 0.0),
                "device_write_mb_s": snap["gauges"].get(
                    "swap/device_write_mb_s", 0.0),
            }
        finally:
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)

    def swap_cycle_run(pipelined, leaves, shardings, compute_s,
                       cycles=5, buffer_count=4, aio_cfg=None):
        """The tier's own cost, isolated: park + [a fixed jitted compute
        burst standing in for the next step's fwd+bwd] + unpark, on the
        real param set. ``exposed_s`` = cycle time minus the burst — the
        swap seconds the step actually pays. Blocking pays write+read
        serially; the pipelined schedule write-behinds into the burst and
        serves the re-read from the pool cache + page-cache window."""
        from deepspeed_tpu.runtime.swap_tensor import PartitionedParamSwapper
        import shutil
        tmp = tempfile.mkdtemp(prefix="dstpu_nvme_cycle_")
        # burst sized to compute_s on this machine (jitted matmul chain)
        import jax.numpy as jnp2
        a = jnp2.asarray(np.random.RandomState(0)
                         .randn(1024, 1024).astype(np.float32))
        burst_fn = jax.jit(lambda x, n: jax.lax.fori_loop(
            0, n, lambda _, y: jnp2.tanh(y @ y) * 0.5 + y * 0.5, x))
        burst_fn(a, 1).block_until_ready()
        t0 = time.perf_counter()
        burst_fn(a, 8).block_until_ready()
        per8 = time.perf_counter() - t0
        n_iter = max(1, int(round(8 * compute_s / max(per8, 1e-6))))
        t0 = time.perf_counter()
        burst_fn(a, n_iter).block_until_ready()
        burst_s = time.perf_counter() - t0
        try:
            sw = PartitionedParamSwapper(
                tmp, aio_config=aio_cfg,
                pipeline_read=pipelined, pipeline_write=pipelined,
                buffer_count=buffer_count)
            sw.write_all(leaves)
            cur = sw.swap_in_device(shardings)
            t_first = None
            ts = []
            for c in range(cycles):
                t0 = time.perf_counter()
                sw.swap_out_device(cur)
                for leaf in cur:
                    leaf.delete()
                # the "next step's compute": write-behind I/O (aio
                # threads + kernel) runs while XLA owns the cores
                burst_fn(a, n_iter).block_until_ready()
                cur = sw.swap_in_device(shardings)
                dt = time.perf_counter() - t0
                if c == 0:
                    t_first = dt
                else:
                    ts.append(dt)
            sw.release()
            cycle = min(ts)
            return {"cycle_s": round(cycle, 3),
                    "burst_s": round(burst_s, 3),
                    "exposed_s": round(max(cycle - burst_s, 0.0), 3),
                    "first_cycle_s": round(t_first, 3)}
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    try:
        blocking = train_run(False)
        pipelined = train_run(True)
        # the honest mode (ISSUE 20): same pipelined schedule, swap
        # files opened O_DIRECT — bytes hit the device, not the page
        # cache, so these are the numbers the 2104.07857 claim is about
        direct = train_run(True, o_direct=True)
        losses_equal = (blocking["first_loss"] == pipelined["first_loss"]
                        and abs(blocking["last_loss"]
                                - pipelined["last_loss"]) < 1e-4)
        losses_equal_direct = (
            direct["first_loss"] == pipelined["first_loss"]
            and abs(direct["last_loss"] - pipelined["last_loss"]) < 1e-4)

        # microbench on the real leaf set (host-side init, no training)
        model = GPT2LMHeadModel(cfg_m)
        params = model.init(
            jax.random.PRNGKey(0),
            np.zeros((1, 8), np.int32))["params"]
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = make_mesh(MeshConfig(data=1), devices=[dev])
        leaves = jax.tree_util.tree_leaves(params)
        shardings = [NamedSharding(mesh, PartitionSpec())] * len(leaves)
        cyc_b = swap_cycle_run(False, leaves, shardings, compute_s=0.4)
        cyc_p = swap_cycle_run(True, leaves, shardings, compute_s=0.4)
        # hot-set pool: buffer_count sized to the leaf count (the
        # reference's generously-sized pinned pool) — every re-read is a
        # cache hit and writes drain behind the next step's compute
        cyc_h = swap_cycle_run(True, leaves, shardings, compute_s=0.4,
                               buffer_count=len(leaves))
        from types import SimpleNamespace
        from deepspeed_tpu.ops.native.aio import o_direct_fallback_latched
        cyc_d = swap_cycle_run(
            True, leaves, shardings, compute_s=0.4,
            aio_cfg=SimpleNamespace(o_direct=True))

        return {
            "params_b": round(cfg_m.num_params() / 1e9, 4),
            "params_on_disk_mb": pipelined["disk_mb"],
            "params_parked_between_steps": bool(
                blocking["parked"] and pipelined["parked"]),
            # headline stays the r5-shape metric, now from the PIPELINED
            # tier; blocking_step_s is the same-harness baseline
            "steady_step_s": pipelined["steady_step_s"],
            "blocking_step_s": blocking["steady_step_s"],
            "step_speedup": round(blocking["steady_step_s"]
                                  / pipelined["steady_step_s"], 3),
            "losses_equal_blocking_vs_pipelined": bool(losses_equal),
            "first_loss": pipelined["first_loss"],
            "last_loss": pipelined["last_loss"],
            # the tier's own cost, arithmetic excluded: one full
            # park+unpark of every leaf (write-behind + cache + sliding
            # read window vs the r5 sync loop)
            "swap_cycle": {
                "blocking_s": cyc_b["cycle_s"],
                "pipelined_s": cyc_p["cycle_s"],
                "hotset_pool_s": cyc_h["cycle_s"],
                "compute_burst_s": cyc_b["burst_s"],
                "blocking_exposed_s": cyc_b["exposed_s"],
                "pipelined_exposed_s": cyc_p["exposed_s"],
                "hotset_pool_exposed_s": cyc_h["exposed_s"],
                # swap seconds the step pays, arithmetic excluded
                "exposed_speedup": round(
                    cyc_b["exposed_s"] / max(cyc_p["exposed_s"], 1e-9), 2),
                "hotset_exposed_speedup": round(
                    cyc_b["exposed_s"] / max(cyc_h["exposed_s"], 1e-9), 2),
                "first_cycle_blocking_s": cyc_b["first_cycle_s"],
                "first_cycle_pipelined_s": cyc_p["first_cycle_s"],
            },
            # ISSUE 20: buffered-vs-direct on the identical schedule.
            # Buffered first reads were page-cache-warm (write_all just
            # populated the cache), so buffered first≈steady is a cache
            # artifact; O_DIRECT first≈steady is the honest version —
            # every pass pays the device, and the ratio should sit near
            # 1.0 because there is no cache to warm
            "o_direct": {
                "steady_step_s": direct["steady_step_s"],
                "step_s_delta_vs_buffered_pct": round(
                    (direct["steady_step_s"]
                     / pipelined["steady_step_s"] - 1) * 100, 1),
                "losses_equal_vs_buffered": bool(losses_equal_direct),
                "stall_s_per_step": direct["stall_s_per_step"],
                "stall_share_of_step": direct["stall_share_of_step"],
                "device_read_mb_s": direct["device_read_mb_s"],
                "device_write_mb_s": direct["device_write_mb_s"],
                "cycle_s": cyc_d["cycle_s"],
                "exposed_s": cyc_d["exposed_s"],
                "first_cycle_s": cyc_d["first_cycle_s"],
                "first_vs_steady_cycle": round(
                    cyc_d["first_cycle_s"] / max(cyc_d["cycle_s"],
                                                 1e-9), 2),
                "fallback_latched": o_direct_fallback_latched(),
            },
            "swap_stall": {
                "blocking_s_per_step": blocking["stall_s_per_step"],
                "pipelined_s_per_step": pipelined["stall_s_per_step"],
                "blocking_share_of_step": blocking["stall_share_of_step"],
                "pipelined_share_of_step":
                    pipelined["stall_share_of_step"],
            },
            "swap_phases": {
                "blocking": {k: blocking[k] for k in
                             ("unpark_s", "park_s",
                              "bytes_read_mb_per_step",
                              "cache_hit_mb_per_step",
                              "bytes_written_mb_per_step")},
                "pipelined": {k: pipelined[k] for k in
                              ("unpark_s", "park_s",
                               "bytes_read_mb_per_step",
                               "cache_hit_mb_per_step",
                               "bytes_written_mb_per_step")},
            },
            "host_rss_growth_mb_over_steps": pipelined["rss_growth_mb"],
            "rss_growth_note": "= param_bytes/step of axon-client h2d "
                               "staging; harness property, not a "
                               "framework leak (perf_tuning r5e)",
            "compute_note": "CPU-only harness: the step is model-"
                            "arithmetic-bound (fwd+bwd ~9s, swap ~0.15s, "
                            "run-to-run step noise ~20%), AND the swap "
                            "files ride the guest page cache (no O_DIRECT"
                            "/per-step fsync), so the kernel already "
                            "write-behinds and every mode is memcpy-"
                            "bound — the pipelined schedule shows up as "
                            "the halved stall share, not a step multiple."
                            " r5's 8.16 s was tunnel-h2d-bound on an axon"
                            " TPU, where the write-behind park (which "
                            "skips the h2d push + d2h re-read round trip "
                            "on host-optimizer engines) is the lever — "
                            "needs a real-chip session to measure",
        }
    except Exception as e:
        return {"skipped": str(e)[:200]}


def bench_nvme_xl(dstpu, make_mesh, MeshConfig, dev):
    """ISSUE 20 acceptance: the 10B+ single-chip run on the honest
    (O_DIRECT) NVMe path. Two legs:

    - **parity**: a small GPT-2 trains with params in memory vs resting
      on NVMe through the O_DIRECT swap tier — identical host-optimizer
      math, so the loss trajectories must match exactly (the direct
      path changes WHERE bytes live, never what they are);
    - **scale**: a 10.6B-parameter tiled bf16 leaf set (GPT-2 shapes at
      n_embd=5120, 33 layers: qkv/proj/mlp_in/mlp_out per layer + a
      row-tiled embedding) parks to disk through a GENERATOR (host
      residency: one leaf), then streams back twice through
      ``swap_in_stream``'s bounded staging window with a host touch +
      sampled content check per leaf. Under O_DIRECT there is no page
      cache to warm, so pass 1 ≈ pass 2 (the buffered tier's 5x
      first-read cliff was a cache artifact), and host RSS stays at
      the staging window no matter the model size.

    Shrink knob: DSTPU_NVME_XL_LAYERS (default 33) scales the layer
    count for CI boxes without 25 GB of scratch disk."""
    import shutil
    import tempfile
    import time
    from types import SimpleNamespace
    import jax.numpy as jnp
    import ml_dtypes
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.ops.native.aio import (
        aligned_empty, o_direct_fallback_latched)
    from deepspeed_tpu.runtime.swap_tensor import PartitionedParamSwapper
    from deepspeed_tpu.telemetry import default_registry

    def rss_mb():
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    return int(line.split()[1]) / 1024
        return 0.0

    # ---- leg 1: small-scale loss parity, in-memory vs nvme+O_DIRECT --
    cfg_m = GPT2Config(vocab_size=2048, n_positions=128, n_embd=256,
                       n_layer=4, n_head=4, dtype=jnp.float32,
                       scan_layers=True)
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, 2048, size=(4, 128))
             .astype(np.int32)}

    def parity_run(nvme, tmp):
        zo = {"stage": 2, "offload_optimizer": {"device": "cpu"}}
        if nvme:
            zo["offload_param"] = {
                "device": "nvme", "nvme_path": tmp,
                "pipeline_read": True, "pipeline_write": True,
                "buffer_count": 4}
        cfg = {
            "train_batch_size": 4,
            "zero_optimization": zo,
            "aio": {"o_direct": bool(nvme)},
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "steps_per_print": 1000,
        }
        default_registry().reset()
        engine, _, _, _ = dstpu.initialize(
            config=cfg, model=GPT2LMHeadModel(cfg_m),
            mesh=make_mesh(MeshConfig(data=1), devices=[dev]))
        return [float(engine.train_batch(batch)) for _ in range(4)]

    tmp = tempfile.mkdtemp(prefix="dstpu_nvme_xl_")
    try:
        mem_losses = parity_run(False, tmp)
        nvme_losses = parity_run(True, tmp)
        parity = all(abs(a - b) < 1e-6
                     for a, b in zip(mem_losses, nvme_losses))

        # ---- leg 2: the 10B+ O_DIRECT stream -------------------------
        E = 5120
        L = int(os.environ.get("DSTPU_NVME_XL_LAYERS", 33))
        vocab = 50304
        dt = np.dtype(ml_dtypes.bfloat16)
        shapes = []
        for _ in range(L):
            shapes += [(E, 3 * E), (E, E), (E, 4 * E), (4 * E, E)]
        rows = vocab
        while rows > 0:                    # row-tiled embedding
            shapes.append((min(rows, E), E))
            rows -= min(rows, E)
        total_params = sum(int(np.prod(s)) for s in shapes)
        total_bytes = total_params * dt.itemsize
        free = shutil.disk_usage(tmp).free
        if free < total_bytes * 1.15:
            return {"skipped": f"needs {total_bytes / 2**30:.1f} GiB "
                               f"scratch, only {free / 2**30:.1f} free",
                    "parity_losses_equal": bool(parity)}

        max_nbytes = max(int(np.prod(s)) * dt.itemsize for s in shapes)
        # one reusable pattern buffer: every leaf is the pattern with
        # its index stamped into the first 8 bytes (cheap to generate,
        # cheap to verify by sample on the way back)
        pat = aligned_empty(max_nbytes)
        pat[:] = np.tile(
            np.frombuffer(np.random.RandomState(7).bytes(1 << 20),
                          np.uint8),
            max_nbytes // (1 << 20) + 1)[:max_nbytes]

        def leaf_bytes(i, nbytes):
            view = pat[:nbytes]
            view[:8] = np.frombuffer(
                np.int64(i).tobytes(), np.uint8)
            return view

        def gen():
            for i, s in enumerate(shapes):
                nb = int(np.prod(s)) * dt.itemsize
                yield leaf_bytes(i, nb).view(dt).reshape(s)

        sw = PartitionedParamSwapper(
            tmp, aio_config=SimpleNamespace(o_direct=True),
            pipeline_read=True, buffer_count=4)
        rss0 = rss_mb()
        t0 = time.perf_counter()
        sw.write_all(gen())
        write_s = time.perf_counter() - t0
        disk = sum(os.path.getsize(sw._path(i))
                   for i in range(len(shapes)))

        def stream_pass():
            t0 = time.perf_counter()
            touched = 0
            verified = 0
            for i, view in sw.swap_in_stream():
                raw = view.view(np.uint8).reshape(-1)
                touched += int(raw[-4096:].sum())   # the host "compute"
                stamp = int(np.frombuffer(raw[:8].tobytes(),
                                          np.int64)[0])
                off = 1 << 16
                ok = (stamp == i and np.array_equal(
                    raw[off:off + 4096], pat[off:off + 4096]))
                verified += int(ok)
            return time.perf_counter() - t0, verified, touched

        pass1_s, ok1, _ = stream_pass()
        pass2_s, ok2, _ = stream_pass()
        rss_peak_growth = rss_mb() - rss0
        sw.release()
        reg = default_registry()
        return {
            "max_params_b": round(total_params / 1e9, 2),
            "leaves": len(shapes),
            "layers": L,
            "dtype": str(dt),
            "disk_gb": round(disk / 2**30, 2),
            "write_s": round(write_s, 1),
            "write_mb_s": round(total_bytes / write_s / 2**20, 1),
            "first_pass_s": round(pass1_s, 1),
            "steady_pass_s": round(pass2_s, 1),
            "read_mb_s_first": round(total_bytes / pass1_s / 2**20, 1),
            "read_mb_s_steady": round(total_bytes / pass2_s / 2**20, 1),
            # ≈1.0 is the point: no page cache, no first-read cliff
            "first_vs_steady_pass": round(pass1_s / pass2_s, 2),
            "leaves_verified_pass1": ok1,
            "leaves_verified_pass2": ok2,
            "host_rss_growth_mb": round(rss_peak_growth, 1),
            "device_read_mb_s_gauge": reg.peek_gauge(
                "swap/device_read_mb_s"),
            "device_write_mb_s_gauge": reg.peek_gauge(
                "swap/device_write_mb_s"),
            "o_direct_fallback_latched": o_direct_fallback_latched(),
            "parity_losses_equal": bool(parity),
            "parity_losses_mem": mem_losses,
            "parity_losses_nvme": nvme_losses,
            "note": "host residency while streaming = the staging "
                    "window (buffer_count slots of the largest leaf), "
                    "not the model: the 10.6B bf16 set is ~20 GiB on "
                    "disk against a window under 1 GiB. On a "
                    "virtualized disk first_vs_steady_pass can exceed "
                    "1 even under O_DIRECT — the guest bypasses ITS "
                    "cache but the virtio host may still serve "
                    "re-reads; the nvme_param o_direct "
                    "first_vs_steady_cycle (fresh files per cycle) is "
                    "the cache-independence pin",
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_bert(dstpu, make_mesh, MeshConfig, dev, batch_size=128, seq=128):
    """BERT-base MLM pretraining step throughput (samples/sec)."""
    import time
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.bert import bert_base, BertForPreTraining, \
        pretraining_loss

    model_cfg = bert_base(dtype=jnp.bfloat16, scan_layers=True)
    model = BertForPreTraining(model_cfg)

    def loss_fn(params, batch):
        out = model.apply({"params": params}, batch["input_ids"],
                          batch["attention_mask"])
        return pretraining_loss(out, batch)

    cfg = {
        "train_batch_size": batch_size,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = dstpu.initialize(
        config=cfg, model=model, loss_fn=loss_fn,
        mesh=make_mesh(MeshConfig(data=1), devices=[dev]))
    rng = np.random.RandomState(0)
    labels = rng.randint(0, model_cfg.vocab_size,
                         size=(batch_size, seq)).astype(np.int32)
    mlm_labels = np.where(rng.rand(batch_size, seq) < 0.15, labels, -100) \
        .astype(np.int32)
    batch = {
        "input_ids": labels,
        "attention_mask": np.ones((batch_size, seq), np.int32),
        "mlm_labels": mlm_labels,
        "nsp_labels": rng.randint(0, 2, size=(batch_size,)).astype(np.int32),
    }
    for _ in range(2):
        loss = engine.train_batch(batch)
    float(jax.device_get(loss))
    iters = 12
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = engine.train_batch(batch)
    float(jax.device_get(loss))
    dt = (time.perf_counter() - t0) / iters
    return round(batch_size / dt, 1)


if __name__ == "__main__":
    sys.exit(main())
