import time, cProfile, pstats, io
import numpy as np
import jax, jax.numpy as jnp
import deepspeed_tpu as dstpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

dev = jax.devices()[0]
mesh = make_mesh(MeshConfig(data=1), devices=[dev])
seq, B = 1024, 8
model_cfg = GPT2Config(vocab_size=50304, n_positions=seq, n_embd=1024,
                       n_layer=24, n_head=16, dtype=jnp.bfloat16,
                       scan_layers=True, remat=True)
cfg = {"train_batch_size": B, "zero_optimization": {"stage": 3},
       "bf16": {"enabled": True}, "gradient_clipping": 1.0,
       "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
       "steps_per_print": 1000}
model = GPT2LMHeadModel(model_cfg)
engine, _, _, _ = dstpu.initialize(config=cfg, model=model, mesh=mesh)
rng = np.random.RandomState(0)
batch = {"input_ids": rng.randint(0, 50304, size=(B, seq)).astype(np.int32)}
for _ in range(3):
    engine.train_batch(batch)
jax.block_until_ready(engine.state.params)

pr = cProfile.Profile()
pr.enable()
for _ in range(5):
    engine.train_batch(batch)
jax.block_until_ready(engine.state.params)
pr.disable()
s = io.StringIO()
pstats.Stats(pr, stream=s).sort_stats("cumulative").print_stats(25)
print(s.getvalue())
