import time
import numpy as np
import jax, jax.numpy as jnp
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel, lm_loss

def timeit(f, *a, n=6):
    float(f(*a)[0]); float(f(*a)[0])
    t0 = time.perf_counter()
    for _ in range(n):
        out = f(*a)
    float(out[0])
    return (time.perf_counter() - t0) / n * 1000

S = 1024
for name, E, L, H, B in (("medium", 1024, 24, 16, 8),
                          ("large", 1280, 36, 20, 8),
                          ("xl-ish", 1600, 24, 25, 4)):
    ids = np.random.randint(0, 50304, (B, S)).astype(np.int32)
    cfg = GPT2Config(vocab_size=50304, n_positions=S, n_embd=E, n_layer=L,
                     n_head=H, dtype=jnp.bfloat16, scan_layers=True, remat=True)
    model = GPT2LMHeadModel(cfg)
    try:
        params = jax.jit(lambda: model.init(jax.random.PRNGKey(0), ids[:1])["params"])()
        jax.block_until_ready(params)
        @jax.jit
        def fwdbwd(p, x):
            def loss_fn(p):
                return lm_loss(model.apply({"params": p}, x), x)
            return jax.value_and_grad(loss_fn)(p)
        tb = timeit(fwdbwd, params, ids)
        fl = 6 * cfg.num_params() * B * S + 12 * L * S * E * B * S
        print(f"{name} (E{E} L{L} B{B}): {tb:.0f}ms mfu {fl/(tb/1e3)/197e12*100:.1f}%", flush=True)
    except Exception as e:
        print(f"{name}: FAILED {str(e)[:80]}", flush=True)
    del model
